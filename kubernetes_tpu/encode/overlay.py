"""Resident overlay planning — three planners, one cluster image.

The live scheduling path keeps ONE device-resident sharded cluster encoding
(the drain context: encode once, fold winners device-side, patch churn).
The background planners — autoscaler scale-up/scale-down simulation,
descheduler eviction validation, gang-defrag prefix probing — historically
re-encoded the whole cluster cold, single-device, private-encoder, every
cycle. This module points them at the resident image instead:

- ``ResidentPlanner`` adapts the scheduler's drain context into the three
  planners' shapes: a row permutation onto the planner's observed node
  list, host alloc/requested mirrors served by the staging shadow (zero
  device round-trips in steady state), and derived pod batches encoded
  against the RESIDENT meta under the cache's encode lock.
- The jitted programs below answer every planner question as ONE warm
  dispatch on the resident tensors: ``_plan_mask_program`` (feasibility
  mask + optional scores for eviction re-placement and scale-down),
  ``_overlay_mask_program`` (K node-group template rows appended to the
  node axis for scale-up — ``with_hypothetical`` without leaving the
  device), and ``_quota_program`` (the per-tenant drain-slot quota plane).
- Anything the resident image cannot answer EXACTLY — tainted context,
  mesh-epoch mismatch, unfolded deltas, node/bound-set skew vs. the
  planner's observation, a template or batch that overflows a resident
  bucket, a pod requesting a resource off the resident axis — DECLINES
  (counted, per planner, per reason) and the caller runs its existing
  cold-encode path. Plans are bit-identical either way: the parity tests
  in tests/test_planner.py fuzz exactly this equivalence.

Two algebraic facts make the overlay exact rather than approximate:

1. Nominee reservations: the resident image may carry an M-bucketed
   nominee plane; the planners' cold encodes carry M=0. Zeroing
   ``nom_valid`` makes the fit filter's reservation prefix-sums the
   identity (every slot's priority collapses to -inf, reserved requests to
   zero), which is bit-identical to an M=0 encode.
2. Resource-axis superset: the resident axis may carry resources no
   current pod requests (historic bound pods). Such a column contributes
   requested=0 for every pod and node, so fit comparisons, score
   fractions (fixed cpu/memory columns) and ledger arithmetic are
   unchanged by the extra column.

The ``label_value_num`` / ``image_sizes`` tables are the one part of the
resident image allowed to go stale (interning appends host-side between
full encodes), so every program takes FRESHLY built tables as inputs —
tiny replicated vectors, rebuilt per dispatch under the encode lock.
"""

from __future__ import annotations

import copy
import logging
import threading
from dataclasses import dataclass
from functools import partial
from types import SimpleNamespace
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_tpu.encode.dictionary import next_bucket
from kubernetes_tpu.encode.scaling import UNLIMITED, scale_allocatable
from kubernetes_tpu.encode.snapshot import EFFECTC, NODE_NAME_LABEL
from kubernetes_tpu.metrics.registry import SCHEDULER_PLANNER_OVERLAY
from kubernetes_tpu.ops.filters import run_filters
from kubernetes_tpu.ops.scores import combined_score

_LOG = logging.getLogger(__name__)

# every ClusterTensors field with the node bucket as axis 0 — the set the
# template overlay widens (matches encode_cluster's node-side fill)
_NODE_AXIS_FIELDS = (
    "allocatable", "requested", "node_valid", "unschedulable",
    "node_labels", "taint_key", "taint_val", "taint_effect", "taint_valid",
    "port_proto", "port_port", "port_ip", "port_valid", "node_images",
    "used_rwo", "used_rwo_valid", "attach_used", "attach_limit",
)


@dataclass
class PlanMeta:
    """Host-side stand-in for ``SnapshotMeta`` on resident planner paths:
    the live node list in the PLANNER's observation order plus the resident
    resource axis — exactly the fields the host-side ledgers, binpacks and
    move records consume (``node_names``/``node_index``/``resources``)."""

    resources: list
    node_names: list
    node_index: dict
    generation: int = 0


# ---- jitted planner programs ------------------------------------------------

@partial(jax.jit, static_argnames=("enabled", "want_scores"))
def _plan_mask_program(ct, pb, label_value_num, image_sizes, enabled,
                       want_scores):
    """Feasibility mask (and optionally scores) for a derived pod batch
    against the resident image: fresh intern tables swapped in, nominee
    plane neutralized (identical to an M=0 cold encode — see module doc)."""
    ct = ct.replace(label_value_num=label_value_num, image_sizes=image_sizes,
                    nom_valid=jnp.zeros_like(ct.nom_valid))
    mask = run_filters(ct, pb, enabled)
    if not want_scores:
        return mask
    return mask, combined_score(ct, pb, mask)


def _overlay_ct(ct, planes):
    """Append the K-bucketed template planes to every node-axis field and
    swap in the fresh tables — ``with_hypothetical`` as a traced program."""
    ext = {f: jnp.concatenate([getattr(ct, f), planes[f]], axis=0)
           for f in _NODE_AXIS_FIELDS}
    return ct.replace(label_value_num=planes["label_value_num"],
                      image_sizes=planes["image_sizes"], **ext)


@jax.jit
def _overlay_ct_program(ct, planes):
    return _overlay_ct(ct, planes)


@jax.jit
def _overlay_mask_program(ct, planes, pb):
    ct2 = _overlay_ct(ct, planes)
    ct2 = ct2.replace(nom_valid=jnp.zeros_like(ct2.nom_valid))
    return run_filters(ct2, pb)


@jax.jit
def _quota_program(victim_tenant, quotas):
    """allowed[v] = this victim's 0-based rank among ITS tenant's victims
    (in eviction order) is below the tenant's quota. -1 tenant or -1 quota
    = unlimited. ONE dispatch decides the whole cycle's quota verdicts."""
    T = quotas.shape[0]
    hot = victim_tenant[:, None] == jnp.arange(T, dtype=victim_tenant.dtype)
    rank = jnp.cumsum(hot.astype(jnp.int32), axis=0) - 1
    my_rank = jnp.sum(jnp.where(hot, rank, 0), axis=1)
    lim = jnp.where(quotas < 0, jnp.int32(UNLIMITED), quotas)
    my_lim = jnp.where(victim_tenant >= 0,
                       lim[jnp.clip(victim_tenant, 0, T - 1)],
                       jnp.int32(UNLIMITED))
    return my_rank < my_lim


@jax.jit
def _without_program(ct, slot_rows, node_rows, req_delta):
    # padding entries carry out-of-bounds indices, which JAX scatters DROP
    requested = ct.requested.at[node_rows].add(-req_delta)
    epod_valid = ct.epod_valid.at[slot_rows].set(False)
    return ct.replace(requested=requested, epod_valid=epod_valid)


def tenant_quota_mask(tenant_ids: list, quotas: list) -> np.ndarray:
    """Device-side per-tenant drain-slot quota plane. ``tenant_ids``: one
    int per victim in eviction order — an index into ``quotas`` (-1 =
    untenanted/unquotaed -> unlimited); ``quotas``: per-tenant eviction
    caps (-1 = unlimited). Returns the allowed[V] verdicts — the caller
    blocks any set containing a disallowed victim, with no host-side
    re-derivation of the arithmetic (power-of-two buckets keep the
    program warm across cycles)."""
    V = next_bucket(len(tenant_ids), minimum=1)
    T = next_bucket(len(quotas), minimum=1)
    vt = np.full(V, -1, np.int32)
    vt[:len(tenant_ids)] = np.asarray(tenant_ids, np.int32)
    q = np.full(T, -1, np.int32)
    q[:len(quotas)] = np.asarray(quotas, np.int32)
    return np.asarray(_quota_program(vt, q))[:len(tenant_ids)]


# ---- host-side builders (call under the owning encoder's lock) --------------

def _fresh_tables(enc, V: int, IMG: int):
    """label_value_num/image_sizes rebuilt from the encoder's CURRENT
    intern tables at the resident bucket widths; None when either table
    outgrew its resident bucket (structural — the next full encode will
    widen it)."""
    if len(enc.values) > V or len(enc._image_sizes) > IMG:
        return None
    lvn = np.full(V, np.nan, np.float32)
    nums = enc.values.numeric_values()
    lvn[:len(nums)] = np.asarray(nums, np.float32)
    isz = np.zeros(IMG, np.float32)
    isz[:len(enc._image_sizes)] = enc._image_sizes
    return lvn, isz


def _template_planes(enc, resources, ct, templates) -> Optional[dict]:
    """Node-axis plane rows for K hypothetical template nodes at the
    RESIDENT bucket widths (same fill logic as ``with_hypothetical``'s
    numpy path), plus fresh tables. None when a template overflows a
    resident bucket (new label key past K, taints past T, value past V)."""
    from kubernetes_tpu.sched.volumebinding import node_attach_limit
    Kdev = ct.node_labels.shape[1]
    Tdev = ct.taint_key.shape[1]
    PRT = ct.port_proto.shape[1]
    I = ct.node_images.shape[1]
    VN = ct.used_rwo.shape[1]
    R = ct.allocatable.shape[1]
    tmpl_labels = [enc._label_ids(n.metadata.labels,
                                  {NODE_NAME_LABEL: n.metadata.name})
                   for n in templates]
    tmpl_taints = [[(enc.keys.intern(t.key), enc.values.intern(t.value),
                     EFFECTC.get(t.effect, 0)) for t in n.spec.taints]
                   for n in templates]
    # only the TEMPLATES' label keys must address node_labels columns —
    # pod-side keys interned after the cluster encode (e.g. a gang label)
    # grow the shared table past Kdev without touching any node row
    if any(kid >= Kdev for ids in tmpl_labels for kid in ids):
        return None
    if max((len(t) for t in tmpl_taints), default=0) > Tdev:
        return None
    tables = _fresh_tables(enc, ct.label_value_num.shape[0],
                           ct.image_sizes.shape[0])
    if tables is None:
        return None
    KB = next_bucket(len(templates), minimum=1)
    planes = dict(
        allocatable=np.zeros((KB, R), np.int32),
        requested=np.zeros((KB, R), np.int32),
        node_valid=np.zeros(KB, bool),
        unschedulable=np.zeros(KB, bool),
        node_labels=np.full((KB, Kdev), -1, np.int32),
        taint_key=np.full((KB, Tdev), -1, np.int32),
        taint_val=np.full((KB, Tdev), -1, np.int32),
        taint_effect=np.full((KB, Tdev), -1, np.int32),
        taint_valid=np.zeros((KB, Tdev), bool),
        port_proto=np.full((KB, PRT), -1, np.int32),
        port_port=np.full((KB, PRT), -1, np.int32),
        port_ip=np.full((KB, PRT), -1, np.int32),
        port_valid=np.zeros((KB, PRT), bool),
        node_images=np.full((KB, I), -1, np.int32),
        used_rwo=np.full((KB, VN), -1, np.int32),
        used_rwo_valid=np.zeros((KB, VN), bool),
        attach_used=np.zeros(KB, np.int32),
        attach_limit=np.full(KB, UNLIMITED, np.int32),
    )
    for k, n in enumerate(templates):
        planes["node_valid"][k] = True
        planes["unschedulable"][k] = n.spec.unschedulable
        alloc = n.allocatable_canonical()
        for r_idx, r in enumerate(resources):
            if r in alloc:
                planes["allocatable"][k, r_idx] = min(
                    scale_allocatable(r, alloc[r]), UNLIMITED)
            elif r == "pods":
                planes["allocatable"][k, r_idx] = UNLIMITED
        for kid, vid in tmpl_labels[k].items():
            planes["node_labels"][k, kid] = vid
        for t_idx, (tk, tv, te) in enumerate(tmpl_taints[k]):
            planes["taint_key"][k, t_idx] = tk
            planes["taint_val"][k, t_idx] = tv
            planes["taint_effect"][k, t_idx] = te
            planes["taint_valid"][k, t_idx] = True
        lim = node_attach_limit(n.status.allocatable)
        if lim >= 0:
            planes["attach_limit"][k] = lim
    planes["label_value_num"], planes["image_sizes"] = tables
    return planes


def resident_with_hypothetical(encoder, ct, meta, nodes):
    """``with_hypothetical`` against a device-resident encoding: template
    planes host-built at the resident widths, appended by ONE jitted
    concatenate — the image never round-trips. Returns (ct_over, rows)
    with ct_over still resident, or None on bucket overflow (the encoder
    method then falls back to the host path). Call under whatever lock
    guards the encoder's intern tables."""
    planes = _template_planes(encoder, meta.resources, ct, nodes)
    if planes is None:
        return None
    N = ct.node_valid.shape[0]
    return _overlay_ct_program(ct, planes), list(range(N, N + len(nodes)))


def resident_without_pods(st, ct, pod_keys):
    """``without_pods`` against a device-resident encoding: the victims'
    request vectors leave ``requested`` and their epod rows invalidate via
    one jitted scatter. ``st``: the encoder's patch state (the caller
    already validated generation/patchability/slot membership)."""
    keys = sorted(set(pod_keys))
    B = next_bucket(len(keys), minimum=1)
    E = ct.epod_valid.shape[0]
    N, R = ct.requested.shape
    slot_rows = np.full(B, E, np.int32)   # out-of-bounds pad: dropped
    node_rows = np.full(B, N, np.int32)
    req_delta = np.zeros((B, R), np.int32)
    for i, k in enumerate(keys):
        slot_rows[i] = st.slot_of[k]
        node_rows[i] = st.slot_node[k]
        req_delta[i] = st.slot_req[k]
    return _without_program(ct, slot_rows, node_rows, req_delta)


# ---- compile accounting -----------------------------------------------------

class CompileCounter:
    """Counts XLA ``backend_compile`` events inside armed windows via
    ``jax.monitoring`` — the FleetChurn compile gate generalized so the
    BackgroundPlanner cadence and the PlannerLoop bench share one
    mechanism for proving a zero-compile steady window."""

    def __init__(self):
        self.count = 0
        self._armed = False
        self._lock = threading.Lock()
        from jax import monitoring
        monitoring.register_event_duration_secs_listener(self._on_event)

    def _on_event(self, event, duration, **kwargs):
        if "backend_compile" in event:
            with self._lock:
                if self._armed:
                    self.count += 1

    def arm(self) -> None:
        with self._lock:
            self._armed = True

    def disarm(self) -> None:
        with self._lock:
            self._armed = False

    def take(self) -> int:
        with self._lock:
            return self.count


# ---- the planner adapter ----------------------------------------------------

class ResidentPlanner:
    """Adapter giving the three background planners resident fast paths.

    ``view_source``: ``Scheduler.resident_plan_view`` — ``() -> (view |
    None, reason)``, the PR-17 ``_resident_wave_view`` contract (untainted
    + mesh-epoch-current + all-folded delta log) with decline reasons.
    ``cache``: the scheduler cache owning the live encoder and encode lock.

    Every method returns None on decline and counts (planner, reason);
    the caller then runs its existing cold-encode path, which produces a
    bit-identical plan — residency is a latency optimization, never a
    semantic fork. Callers report success with ``hit(ctx)`` exactly once
    per fully-resident plan.
    """

    def __init__(self, view_source: Callable, cache):
        self._view_source = view_source
        self.cache = cache
        self.hits: dict = {}
        self.declines: dict = {}

    # -- accounting ---------------------------------------------------------

    def _decline(self, planner: str, reason: str):
        d = self.declines.setdefault(planner, {})
        d[reason] = d.get(reason, 0) + 1
        SCHEDULER_PLANNER_OVERLAY.inc({"planner": planner,
                                       "outcome": "decline"})
        return None

    def hit(self, ctx: dict) -> None:
        planner = ctx["planner"]
        self.hits[planner] = self.hits.get(planner, 0) + 1
        SCHEDULER_PLANNER_OVERLAY.inc({"planner": planner, "outcome": "hit"})

    def stats(self) -> dict:
        return {"hits": dict(self.hits),
                "declines": {k: dict(v) for k, v in self.declines.items()}}

    # -- view ---------------------------------------------------------------

    def plan_view(self, nodes, bound_pods, planner: str) -> Optional[dict]:
        """The resident image row-permuted onto THIS planner's observed
        node list, or None. Beyond the scheduler-side freshness checks,
        the planner's observation must agree with the image: same node
        set, same bound-pod set (the planners observe through the API
        client; any skew vs. the cache means the cold encode would see a
        different cluster than the image holds)."""
        view, reason = self._view_source()
        if view is None:
            return self._decline(planner, reason)
        meta = view["meta"]
        cs = view["cs"]
        names = {n.metadata.name for n in nodes}
        if names != {n.metadata.name for n in view["nodes"]}:
            return self._decline(planner, "node_set_skew")
        bound_keys = {p.key for p in bound_pods
                      if p.spec.node_name in names}
        if bound_keys != set(cs.slot_of):
            return self._decline(planner, "bound_set_skew")
        rows = np.asarray([meta.node_index[n.metadata.name] for n in nodes],
                          np.int32)
        plan_meta = PlanMeta(
            resources=list(cs.resources),
            node_names=[n.metadata.name for n in nodes],
            node_index={n.metadata.name: i for i, n in enumerate(nodes)},
            generation=meta.generation)
        return {"view": view, "ct": view["ct"], "meta": meta, "cs": cs,
                "rows": rows, "mesh": view.get("mesh"),
                "plan_meta": plan_meta, "planner": planner}

    # -- cluster totals ------------------------------------------------------

    def cluster_arrays(self, ctx: dict):
        """(allocatable, requested) int64 [N_live, R_resident] in the
        planner's node order — served from the staging shadow's host
        mirrors (zero device traffic) or one device_get fallback."""
        view = ctx["view"]
        cs = ctx["cs"]
        got = None
        shadow = view.get("shadow")
        if shadow is not None:
            shadow.catch_up(
                lambda p: self.cache.request_vector(p, cs.resources))
            got = shadow.arrays()
        if got is None:
            try:
                # ktpu-lint: disable=KTL005 -- shadow-miss fallback only; steady state serves totals from the staging shadow's host mirrors (PlannerLoop gates the window at zero declines)
                got = jax.device_get(
                    (ctx["ct"].allocatable, ctx["ct"].requested))
            except Exception:
                _LOG.exception("resident totals readback failed; planner "
                               "falls back to the cold encode")
                return self._decline(ctx["planner"], "readback")
        alloc_res, req_res = got
        rows = ctx["rows"]
        return (np.asarray(alloc_res, np.int64)[rows],
                np.asarray(req_res, np.int64)[rows])

    # -- derived pod batches -------------------------------------------------

    def _covered(self, enc, pods, resources) -> bool:
        res = set(resources)
        for p in pods:
            if any(r not in res for r in enc._effective_requests(p)):
                return False
        return True

    def pod_batch(self, ctx: dict, pods):
        """Encode a derived batch (unpinned victims, gang pods, pending
        pods) against the RESIDENT meta under the encode lock, plus fresh
        tables. Declines when a pod requests a resource off the resident
        axis (encode_pods would silently drop it) or a table outgrew its
        bucket. The meta is shallow-copied: encode_pods stamps
        ``meta.pod_keys`` and the drain's own meta must not see it."""
        meta = ctx["meta"]
        ct = ctx["ct"]
        V = ct.label_value_num.shape[0]
        IMG = ct.image_sizes.shape[0]

        def fn(enc):
            if not self._covered(enc, pods, meta.resources):
                return "resource_axis"
            pb = enc.encode_pods(list(pods), copy.copy(meta),
                                 cache_rows=False)
            tables = _fresh_tables(enc, V, IMG)
            if tables is None:
                return "table_bucket"
            return pb, tables

        out = self.cache.with_encoder(fn)
        if isinstance(out, str):
            return self._decline(ctx["planner"], out)
        return out

    # -- warm dispatches -----------------------------------------------------

    def mask_scores(self, ctx: dict, pods, enabled=None,
                    want_scores: bool = False):
        """ONE jitted dispatch answering a batch's feasibility (and
        optionally scores) against the resident image. Returns
        (mask [P, N_live], scores [P, N_live] | None, reqs [P, R] int64)
        gathered into the planner's node order, or None on decline."""
        if not pods:
            n = len(ctx["plan_meta"].node_names)
            return (np.zeros((0, n), bool), None,
                    np.zeros((0, len(ctx["plan_meta"].resources)), np.int64))
        out = self.pod_batch(ctx, pods)
        if out is None:
            return None
        pb, (lvn, isz) = out
        P = len(pods)
        reqs = np.asarray(pb.requests[:P], np.int64)
        mesh = ctx.get("mesh")
        if mesh is not None:
            from kubernetes_tpu.parallel.mesh import replicated, shard_batch
            pb = shard_batch(mesh, pb)
            rep = replicated(mesh)
            lvn = jax.device_put(lvn, rep)
            isz = jax.device_put(isz, rep)
        res = _plan_mask_program(ctx["ct"], pb, lvn, isz, enabled,
                                 want_scores)
        rows = ctx["rows"]
        if want_scores:
            mask, scores = res
            return (np.asarray(mask)[:P][:, rows],
                    np.asarray(scores)[:P][:, rows], reqs)
        return np.asarray(res)[:P][:, rows], None, reqs

    def overlay_mask(self, ctx: dict, templates, pods):
        """Scale-up: K template rows appended to the resident image, ONE
        jitted run_filters over every (pending pod x candidate) question.
        Returns (mask [P, N_live + K] — live columns first, template
        columns after in group order — caps [K, R] and reqs [P, R], both
        int64 on the resident resource axis), or None."""
        if not pods or not templates:
            return None
        meta = ctx["meta"]
        ct = ctx["ct"]

        def fn(enc):
            if not self._covered(enc, pods, meta.resources):
                return "resource_axis"
            planes = _template_planes(enc, meta.resources, ct, templates)
            if planes is None:
                return "template_bucket"
            pb = enc.encode_pods(list(pods), copy.copy(meta),
                                 cache_rows=False)
            return planes, pb

        out = self.cache.with_encoder(fn)
        if isinstance(out, str):
            return self._decline(ctx["planner"], out)
        planes, pb = out
        P = len(pods)
        K = len(templates)
        caps = planes["allocatable"][:K].astype(np.int64)
        reqs = np.asarray(pb.requests[:P], np.int64)
        mesh = ctx.get("mesh")
        planes_in = planes
        if mesh is not None:
            from kubernetes_tpu.parallel.mesh import replicated, shard_batch
            pb = shard_batch(mesh, pb)
            rep = replicated(mesh)
            planes_in = {k: jax.device_put(v, rep)
                         for k, v in planes.items()}
        mask = np.asarray(_overlay_mask_program(ctx["ct"], planes_in, pb))
        N = ct.node_valid.shape[0]
        live = mask[:P][:, ctx["rows"]]
        tmpl = mask[:P, N:N + K]
        return np.concatenate([live, tmpl], axis=1), caps, reqs
