"""Host-side term preprocessing shared by the oracle and the tensor encoder.

This is the analog of the reference's PreFilter-time term normalization
(``pkg/scheduler/framework/types.go`` ``newAffinityTerm`` /
``podtopologyspread/common.go`` ``buildDefaultConstraints``):

- ``matchLabelKeys`` / ``mismatchLabelKeys`` merge the term-owning pod's
  label values into the term's label selector as In / NotIn requirements
  (MatchLabelKeysInPodAffinity, MatchLabelKeysInPodTopologySpread). Keys the
  owning pod doesn't carry are skipped, matching upstream.
- ``namespaces`` + ``namespaceSelector`` resolve to a concrete namespace-name
  set against a snapshot of Namespace labels
  (``mergeAffinityTermNamespacesIfNotEmpty``): both unset means "the owning
  pod's own namespace"; a non-nil selector ORs its matches with the explicit
  list, and the EMPTY selector {} matches every namespace.

Keeping this in one place guarantees the serial oracle and the TPU encoder
agree on the *effective* terms — the tensor path then only has to implement
integer-set matching.
"""

from __future__ import annotations

from typing import Optional

from kubernetes_tpu.api.selectors import label_selector_matches
from kubernetes_tpu.api.types import (
    OP_IN,
    OP_NOT_IN,
    LabelSelector,
    PodAffinityTerm,
    Requirement,
    TopologySpreadConstraint,
)


def effective_label_selector(
        selector: Optional[LabelSelector],
        match_label_keys: list[str],
        mismatch_label_keys: list[str],
        owner_labels: dict[str, str]) -> Optional[LabelSelector]:
    """Merge (mis)matchLabelKeys into ``selector`` using the term-owning
    pod's labels. A nil selector stays nil (it matches nothing; upstream
    validation forbids matchLabelKeys without a selector anyway)."""
    if selector is None or not (match_label_keys or mismatch_label_keys):
        return selector
    extra = []
    for k in match_label_keys:
        if k in owner_labels:
            extra.append(Requirement(k, OP_IN, [owner_labels[k]]))
    for k in mismatch_label_keys:
        if k in owner_labels:
            extra.append(Requirement(k, OP_NOT_IN, [owner_labels[k]]))
    if not extra:
        return selector
    return LabelSelector(
        match_labels=dict(selector.match_labels),
        match_expressions=list(selector.match_expressions) + extra,
    )


def affinity_term_selector(term: PodAffinityTerm,
                           owner_labels: dict[str, str]) -> Optional[LabelSelector]:
    """The term's effective selector after matchLabelKeys merging."""
    return effective_label_selector(
        term.label_selector, term.match_label_keys,
        term.mismatch_label_keys, owner_labels)


def spread_selector(sc: TopologySpreadConstraint,
                    pod_labels: dict[str, str]) -> Optional[LabelSelector]:
    """The constraint's effective selector after matchLabelKeys merging."""
    return effective_label_selector(
        sc.label_selector, sc.match_label_keys, [], pod_labels)


def resolve_term_namespaces(
        term: PodAffinityTerm, own_ns: str,
        namespace_labels: dict[str, dict[str, str]]) -> Optional[frozenset]:
    """Concrete namespace-name set a term applies to, or None meaning "the
    owning pod's own namespace" (the implicit default).

    ``namespace_labels`` maps namespace name -> its labels (the
    GetNamespaceLabelsSnapshot analog). The owning pod's namespace is always
    resolvable even if absent from the map.

    Fleet isolation: when the owning namespace carries the
    ``kubernetes-tpu.io/tenant`` label, a namespaceSelector only matches
    namespaces of the SAME tenant — affinity terms must never couple one
    tenant's pods to a sibling's, no matter how its namespace labels look.
    Untenanted owners keep the pre-fleet behavior exactly.
    """
    if not term.namespaces and term.namespace_selector is None:
        return None
    # local import: snapshot.py imports this module at load time
    from kubernetes_tpu.encode.snapshot import TENANT_LABEL
    own_tenant = (namespace_labels.get(own_ns) or {}).get(TENANT_LABEL)
    names = set(term.namespaces)
    sel = term.namespace_selector
    if sel is not None:
        for ns, labels in namespace_labels.items():
            if own_tenant is not None \
                    and (labels or {}).get(TENANT_LABEL) != own_tenant:
                continue  # tenant-scoped: selectors never cross tenants
            if label_selector_matches(sel, labels or {}):
                names.add(ns)
        # A namespace_labels snapshot that doesn't know own_ns would silently
        # drop self-namespace matches; resolve it explicitly against empty
        # labels (only an empty or purely negative selector can match).
        if own_ns not in namespace_labels and label_selector_matches(sel, {}):
            names.add(own_ns)
    return frozenset(names)
