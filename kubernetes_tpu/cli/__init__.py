"""CLI — ktpu, the kubectl analog (SURVEY §2.5)."""

from kubernetes_tpu.cli.ktpu import main

__all__ = ["main"]
