"""Cluster bootstrap — the kubeadm analog.

Reference: ``cmd/kubeadm`` (init/join phases standing up the control plane
and joining nodes). Here a "cluster" is one process: ``init`` boots the
API server (optionally durable + authenticated), the controller manager,
and the TPU scheduler; ``join`` attaches hollow kubelets to a running
server. ``LocalCluster`` is the library form the CLI wraps — tests and
demos boot a full cluster in a few lines:

    from kubernetes_tpu.cli.cluster import LocalCluster
    with LocalCluster(nodes=3) as c:
        c.client.pods().create({...})

CLI:
    ktpu-up init [--nodes N] [--data-dir DIR] [--auth] [--port P]
    ktpu-up join --server URL [--nodes N] [--name-prefix worker]
"""

from __future__ import annotations

import argparse
import secrets as _secrets
import signal
import sys
import threading
from typing import Optional

from kubernetes_tpu.client.clientset import HTTPClient
from kubernetes_tpu.controllers import ControllerManager
from kubernetes_tpu.kubelet import HollowNode
from kubernetes_tpu.sched.runner import SchedulerRunner
from kubernetes_tpu.store.apiserver import APIServer


class LocalCluster:
    """Control plane + N hollow nodes in-process (kubeadm init + joins).

    Nothing runs until ``start()`` — constructing is side-effect free, and
    a failure mid-start tears down whatever came up.
    """

    def __init__(self, nodes: int = 3, data_dir: Optional[str] = None,
                 auth: bool = False, port: int = 0,
                 node_allocatable: Optional[dict] = None,
                 exit_after: Optional[float] = None,
                 scheduler_cfg=None, registry=None):
        self._cfg = dict(nodes=nodes, data_dir=data_dir, auth=auth, port=port)
        self._alloc = node_allocatable  # None = Kubelet's own default
        self._exit_after = exit_after
        self._scheduler_cfg = scheduler_cfg
        self._registry = registry
        self.server: Optional[APIServer] = None
        self.client: Optional[HTTPClient] = None
        self.runner: Optional[SchedulerRunner] = None
        self.manager: Optional[ControllerManager] = None
        self.kubelets: list[HollowNode] = []
        self.admin_token: Optional[str] = None

    def start(self) -> "LocalCluster":
        try:
            self.server = APIServer(port=self._cfg["port"],
                                    data_dir=self._cfg["data_dir"])
            token = None
            if self._cfg["auth"]:
                # mint a bootstrap superuser credential (kubeadm's
                # admin.conf): system:masters bypasses RBAC entirely, so
                # the in-process components can do their jobs
                self.server.enable_auth()
                token = "ktpu-admin-" + _secrets.token_hex(16)
                self.server.authenticator.add(
                    token, ("system:admin", ("system:masters",)))
                self.admin_token = token
            self.server.enable_admission()
            self.server.start()
            self.client = HTTPClient(self.server.url, token=token)
            self.runner = SchedulerRunner(self.client, cfg=self._scheduler_cfg,
                                          registry=self._registry)
            from kubernetes_tpu.controllers.manager import (
                CLOUD_CONTROLLERS, DEFAULT_CONTROLLERS)
            # cluster-up runs the cloud loops too: this IS the cloud here
            # (nodeipam carves podCIDRs, route flips NetworkUnavailable,
            # service-lb hands out ingress IPs)
            self.manager = ControllerManager(
                self.client,
                controllers=DEFAULT_CONTROLLERS + CLOUD_CONTROLLERS)
            self.runner.start()
            self.manager.start()
            for i in range(self._cfg["nodes"]):
                self.add_node(f"node-{i}")
        except Exception:
            self.stop()
            raise
        return self

    def add_node(self, name: str) -> HollowNode:
        """The `join` phase: register + run one hollow kubelet."""
        kw = {} if self._alloc is None else {"allocatable": dict(self._alloc)}
        node = HollowNode(self.client, name, exit_after=self._exit_after, **kw)
        node.start()
        self.kubelets.append(node)
        return node

    def stop(self) -> None:
        for k in self.kubelets:
            k.stop()
        self.kubelets = []
        if self.manager is not None:
            self.manager.stop()
        if self.runner is not None:
            self.runner.stop()
        if self.server is not None:
            self.server.stop()

    def __enter__(self) -> "LocalCluster":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def join(server_url: str, n: int = 1, name_prefix: str = "worker",
         allocatable: Optional[dict] = None,
         token: Optional[str] = None) -> list[HollowNode]:
    """Attach hollow kubelets to an already-running server."""
    client = HTTPClient(server_url, token=token)
    nodes = []
    for i in range(n):
        kw = {} if allocatable is None else {"allocatable": dict(allocatable)}
        node = HollowNode(client, f"{name_prefix}-{i}", **kw)
        node.start()
        nodes.append(node)
    return nodes


def main(argv=None, out=None) -> int:
    out = out or sys.stdout
    ap = argparse.ArgumentParser(prog="ktpu-up")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p_init = sub.add_parser("init", help="boot control plane + hollow nodes")
    p_init.add_argument("--nodes", type=int, default=3)
    p_init.add_argument("--data-dir", default=None,
                        help="durable store directory (restarts keep state)")
    p_init.add_argument("--auth", action="store_true",
                        help="enable authn/RBAC/audit chain")
    p_init.add_argument("--port", type=int, default=0)
    p_join = sub.add_parser("join", help="attach hollow nodes to a server")
    p_join.add_argument("--server", required=True)
    p_join.add_argument("--nodes", type=int, default=1)
    p_join.add_argument("--name-prefix", default="worker")
    p_join.add_argument("--token", default=None,
                        help="bearer token (required against --auth servers)")
    args = ap.parse_args(argv)

    stop = threading.Event()
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    signal.signal(signal.SIGTERM, lambda *a: stop.set())

    if args.cmd == "init":
        cluster = LocalCluster(nodes=args.nodes, data_dir=args.data_dir,
                               auth=args.auth, port=args.port).start()
        out.write(f"control plane up: {cluster.server.url}\n")
        if cluster.admin_token:
            out.write(f"admin token: {cluster.admin_token}\n")
        out.write(f"nodes: {[k.kubelet.node_name for k in cluster.kubelets]}\n")
        out.flush()
        stop.wait()
        cluster.stop()
    else:
        nodes = join(args.server, n=args.nodes, name_prefix=args.name_prefix,
                     token=args.token)
        out.write(f"joined: {[n.kubelet.node_name for n in nodes]}\n")
        out.flush()
        stop.wait()
        for n in nodes:
            n.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
