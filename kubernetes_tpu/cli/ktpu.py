"""ktpu — the kubectl analog.

Reference shape: ``staging/src/k8s.io/kubectl/pkg/cmd/`` (cobra command tree;
``get`` printers in ``pkg/cmd/get``, ``apply`` in ``cmd/apply/apply.go`` via
resource.Builder over multi-doc YAML, ``scale``, ``cordon``/``drain`` in
``cmd/drain``). argparse stands in for cobra; the server is any running
``kubernetes_tpu.store.apiserver.APIServer``.

Usage:
  ktpu --server http://127.0.0.1:8001 get pods [-n NS] [-o json|yaml|wide]
  ktpu apply -f manifest.yaml            # create-or-update, multi-doc
  ktpu delete pod NAME | ktpu delete -f manifest.yaml
  ktpu describe pod NAME
  ktpu scale deployment NAME --replicas N
  ktpu cordon NODE / ktpu uncordon NODE
  ktpu drain NODE
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Optional

from kubernetes_tpu.client.clientset import ApiError, HTTPClient
from kubernetes_tpu.store.apiserver import ALL_RESOURCES, KIND_TO_PLURAL

# singular/short aliases -> plural (kubectl's RESTMapper shortcuts)
ALIASES = {
    "po": "pods", "pod": "pods",
    "no": "nodes", "node": "nodes",
    "svc": "services", "service": "services",
    "ep": "endpoints",
    "deploy": "deployments", "deployment": "deployments",
    "rs": "replicasets", "replicaset": "replicasets",
    "sts": "statefulsets", "statefulset": "statefulsets",
    "ds": "daemonsets", "daemonset": "daemonsets",
    "job": "jobs",
    "cm": "configmaps", "configmap": "configmaps",
    "ns": "namespaces", "namespace": "namespaces",
    "lease": "leases",
}


def resolve_plural(res: str, client: Optional[HTTPClient] = None) -> str:
    res = res.lower()
    plural = ALIASES.get(res, res)
    if plural in ALL_RESOURCES:
        return plural
    # maybe a custom resource: sweep the server's CRDs (RESTMapper reload)
    if client is not None:
        try:
            client.discover_custom()
        except ApiError:
            pass
        if client.custom_lookup(plural) is not None:
            return plural
    raise SystemExit(f"error: unknown resource type {res!r}")


def _kind_info(client: HTTPClient, plural: str):
    """-> (kind, namespaced) for built-in or discovered custom resources."""
    reg = ALL_RESOURCES.get(plural) or client.custom_lookup(plural)
    return reg[0], reg[1]


def kind_to_plural(client: HTTPClient, kind: str) -> Optional[str]:
    plural = KIND_TO_PLURAL.get(kind)
    if plural is not None:
        return plural
    try:
        client.discover_custom()
    except ApiError:
        return None
    return client.custom_kind_to_plural(kind)


def obj_age(obj: dict) -> str:
    ts = (obj.get("metadata") or {}).get("creationTimestamp")
    if not ts:
        return "<unknown>"
    secs = max(0, int(time.time() - float(ts)))
    for unit, div in (("d", 86400), ("h", 3600), ("m", 60)):
        if secs >= div:
            return f"{secs // div}{unit}"
    return f"{secs}s"


# ---------------------------------------------------------------- printers

def _pod_row(o: dict, wide: bool) -> list[str]:
    st = o.get("status") or {}
    ready = sum(1 for c in st.get("conditions") or []
                if c.get("type") == "Ready" and c.get("status") == "True")
    total = len((o.get("spec") or {}).get("containers") or []) or 1
    row = [o["metadata"]["name"], f"{ready}/{1 if total == 0 else total}",
           st.get("phase", "Unknown"), obj_age(o)]
    if wide:
        row += [st.get("podIP", "<none>"),
                (o.get("spec") or {}).get("nodeName", "<none>")]
    return row


def _node_row(o: dict, wide: bool) -> list[str]:
    conds = (o.get("status") or {}).get("conditions") or []
    ready = any(c.get("type") == "Ready" and c.get("status") == "True"
                for c in conds)
    status = "Ready" if ready else "NotReady"
    if (o.get("spec") or {}).get("unschedulable"):
        status += ",SchedulingDisabled"
    return [o["metadata"]["name"], status, obj_age(o)]


def _workload_row(o: dict, wide: bool) -> list[str]:
    spec_n = (o.get("spec") or {}).get("replicas", 1)
    st = o.get("status") or {}
    return [o["metadata"]["name"],
            f"{st.get('readyReplicas', 0)}/{spec_n}",
            str(st.get("updatedReplicas", st.get("replicas", 0))),
            obj_age(o)]


def _svc_row(o: dict, wide: bool) -> list[str]:
    spec = o.get("spec") or {}
    ports = ",".join(f"{p.get('port')}/{p.get('protocol', 'TCP')}"
                     for p in spec.get("ports") or [])
    return [o["metadata"]["name"], spec.get("type", "ClusterIP"),
            spec.get("clusterIP", "<none>"), ports or "<none>", obj_age(o)]


def _default_row(o: dict, wide: bool) -> list[str]:
    return [o["metadata"]["name"], obj_age(o)]


PRINTERS = {
    "pods": (["NAME", "READY", "STATUS", "AGE"],
             ["NAME", "READY", "STATUS", "AGE", "IP", "NODE"], _pod_row),
    "nodes": (["NAME", "STATUS", "AGE"], ["NAME", "STATUS", "AGE"], _node_row),
    "services": (["NAME", "TYPE", "CLUSTER-IP", "PORT(S)", "AGE"],
                 ["NAME", "TYPE", "CLUSTER-IP", "PORT(S)", "AGE"], _svc_row),
    "deployments": (["NAME", "READY", "UP-TO-DATE", "AGE"],
                    ["NAME", "READY", "UP-TO-DATE", "AGE"], _workload_row),
    "replicasets": (["NAME", "READY", "CURRENT", "AGE"],
                    ["NAME", "READY", "CURRENT", "AGE"], _workload_row),
    "statefulsets": (["NAME", "READY", "CURRENT", "AGE"],
                     ["NAME", "READY", "CURRENT", "AGE"], _workload_row),
}


def print_table(plural: str, items: list[dict], out, wide: bool = False):
    headers, wide_headers, row_fn = PRINTERS.get(
        plural, (["NAME", "AGE"], ["NAME", "AGE"], _default_row))
    headers = wide_headers if wide else headers
    rows = [row_fn(o, wide) for o in items]
    widths = [max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
              for i, h in enumerate(headers)]
    out.write("  ".join(h.ljust(w) for h, w in zip(headers, widths)).rstrip() + "\n")
    for r in rows:
        out.write("  ".join(str(c).ljust(w) for c, w in zip(r, widths)).rstrip() + "\n")


# --------------------------------------------------------------- commands

def load_manifests(path: str) -> list[dict]:
    import yaml
    text = sys.stdin.read() if path == "-" else open(path).read()
    return [d for d in yaml.safe_load_all(text) if d]


def cmd_get(client: HTTPClient, args, out) -> int:
    plural = resolve_plural(args.resource, client)
    _, namespaced = _kind_info(client, plural)
    ns = None if args.all_namespaces else (args.namespace if namespaced else None)
    res = client.resource(plural, ns)
    if args.name:
        items = [res.get(args.name)]
    else:
        items = res.list(label_selector=args.selector)
    if args.output == "json":
        out.write(json.dumps(items[0] if args.name else
                             {"kind": "List", "items": items}, indent=2) + "\n")
    elif args.output == "yaml":
        import yaml
        yaml.safe_dump(items[0] if args.name else {"kind": "List", "items": items},
                       out, sort_keys=False)
    else:
        print_table(plural, items, out, wide=args.output == "wide")
    return 0


def cmd_apply(client: HTTPClient, args, out) -> int:
    rc = 0
    for doc in load_manifests(args.filename):
        kind = doc.get("kind", "")
        plural = kind_to_plural(client, kind)
        if plural is None:
            out.write(f"error: unknown kind {kind!r}\n")
            rc = 1
            continue
        _, namespaced = _kind_info(client, plural)
        md = doc.setdefault("metadata", {})
        ns = md.get("namespace", args.namespace) if namespaced else None
        if namespaced:
            md.setdefault("namespace", ns)
        res = client.resource(plural, ns)
        name = md.get("name", "")
        if getattr(args, "server_side", False):
            # kubectl apply --server-side: the server owns the merge via
            # managedFields (store/apply.py); conflicts 409 unless forced
            try:
                res.apply(doc, field_manager=args.field_manager,
                          force=args.force_conflicts)
                out.write(f"{plural[:-1]}/{name} serverside-applied\n")
            except ApiError as e:
                out.write(f"error: {e}\n")
                rc = 1
            continue
        try:
            current = res.get(name)
        except ApiError as e:
            if e.code != 404:
                raise
            res.create(doc)
            out.write(f"{plural[:-1]}/{name} created\n")
            continue
        # apply = server-side merge of desired onto live (fieldmanager
        # analog: desired spec/labels/annotations win; status/identity kept)
        merged = dict(current)
        for k, v in doc.items():
            if k in ("status",):
                continue
            if k == "metadata":
                m = dict(current.get("metadata") or {})
                for mk in ("labels", "annotations"):
                    if mk in v:
                        m[mk] = v[mk]
                merged["metadata"] = m
            else:
                merged[k] = v
        res.update(merged)
        out.write(f"{plural[:-1]}/{name} configured\n")
    return rc


def cmd_delete(client: HTTPClient, args, out) -> int:
    targets: list[tuple[str, Optional[str], str]] = []
    if args.filename:
        for doc in load_manifests(args.filename):
            plural = kind_to_plural(client, doc.get("kind", ""))
            if plural is None:
                continue
            _, namespaced = _kind_info(client, plural)
            md = doc.get("metadata") or {}
            targets.append((plural,
                            md.get("namespace", args.namespace) if namespaced else None,
                            md.get("name", "")))
    else:
        plural = resolve_plural(args.resource, client)
        _, namespaced = _kind_info(client, plural)
        targets.append((plural, args.namespace if namespaced else None, args.name))
    policy = {"foreground": "Foreground",
              "orphan": "Orphan"}.get(getattr(args, "cascade", "background"))
    for plural, ns, name in targets:
        try:
            client.resource(plural, ns).delete(
                name, propagation_policy=policy)
            out.write(f"{plural[:-1]}/{name} deleted\n")
        except ApiError as e:
            if e.code != 404:
                raise
            out.write(f"{plural[:-1]}/{name} not found\n")
    return 0


def cmd_describe(client: HTTPClient, args, out) -> int:
    plural = resolve_plural(args.resource, client)
    _, namespaced = _kind_info(client, plural)
    obj = client.resource(plural, args.namespace if namespaced else None).get(args.name)
    md = obj.get("metadata") or {}
    out.write(f"Name:         {md.get('name')}\n")
    if namespaced:
        out.write(f"Namespace:    {md.get('namespace')}\n")
    out.write(f"UID:          {md.get('uid')}\n")
    if md.get("labels"):
        out.write("Labels:       " + ",".join(f"{k}={v}" for k, v in
                                              sorted(md["labels"].items())) + "\n")
    if plural == "pods":
        spec, st = obj.get("spec") or {}, obj.get("status") or {}
        out.write(f"Node:         {spec.get('nodeName', '<none>')}\n")
        out.write(f"Status:       {st.get('phase', 'Unknown')}\n")
        out.write(f"IP:           {st.get('podIP', '<none>')}\n")
        out.write("Containers:\n")
        for c in spec.get("containers") or []:
            out.write(f"  {c.get('name')}:\n    Image: {c.get('image', '<none>')}\n")
            reqs = (c.get("resources") or {}).get("requests") or {}
            if reqs:
                out.write("    Requests: " + ", ".join(
                    f"{k}={v}" for k, v in sorted(reqs.items())) + "\n")
        if st.get("conditions"):
            out.write("Conditions:\n")
            for c in st["conditions"]:
                out.write(f"  {c.get('type')}: {c.get('status')}\n")
        from kubernetes_tpu.utils.events import events_for
        evs = events_for(client, md.get("namespace", "default"),
                         md.get("name", ""), uid=md.get("uid"))
        if evs:
            out.write("Events:\n")
            for e in evs:
                count = e.get("count", 1)
                suffix = f" (x{count})" if count > 1 else ""
                out.write(f"  {e.get('type')}  {e.get('reason')}  "
                          f"{e.get('message')}{suffix}\n")
    else:
        import yaml
        out.write("Spec:\n")
        yaml.safe_dump(obj.get("spec") or {}, out, sort_keys=False, indent=2)
        out.write("Status:\n")
        yaml.safe_dump(obj.get("status") or {}, out, sort_keys=False, indent=2)
    return 0


def cmd_scale(client: HTTPClient, args, out) -> int:
    """kubectl scale via the /scale subresource (ScaleREST) — the same
    interface HPA drives, touching only spec.replicas."""
    plural = resolve_plural(args.resource, client)
    res = client.resource(plural, args.namespace)
    try:
        res.update_scale(args.name, args.replicas)
    except ApiError as e:
        if e.code != 404:
            raise
        # kinds without a scale subresource (CRDs): plain spec update
        obj = res.get(args.name)
        obj.setdefault("spec", {})["replicas"] = args.replicas
        res.update(obj)
    out.write(f"{plural[:-1]}/{args.name} scaled\n")
    return 0


def _set_unschedulable(client: HTTPClient, name: str, flag: bool, out) -> int:
    node = client.nodes().get(name)
    node.setdefault("spec", {})["unschedulable"] = flag
    client.nodes().update(node)
    out.write(f"node/{name} {'cordoned' if flag else 'uncordoned'}\n")
    return 0


def cmd_drain(client: HTTPClient, args, out) -> int:
    _set_unschedulable(client, args.name, True, out)
    for p in client.resource("pods", None).list(
            field_selector=f"spec.nodeName={args.name}"):
        md = p["metadata"]
        # daemon pods are not drained (kubectl drain --ignore-daemonsets)
        refs = md.get("ownerReferences") or []
        if any(r.get("kind") == "DaemonSet" for r in refs):
            continue
        client.pods(md.get("namespace", "default")).evict(md["name"])
        out.write(f"pod/{md['name']} evicted\n")
    return 0


def cmd_logs(client: HTTPClient, args, out) -> int:
    """kubectl logs analog: apiserver -> kubelet containerLogs proxy."""
    out.write(client.pod_logs(args.namespace, args.name,
                              container=args.container or ""))
    return 0


def cmd_exec(client: HTTPClient, args, out) -> int:
    """kubectl exec analog (ExecSync shape: command in, output + code)."""
    res = client.pod_exec(args.namespace, args.name, args.command,
                          container=args.container or "")
    out.write(res.get("output", ""))
    return int(res.get("exit_code", 1))


def cmd_port_forward(client: HTTPClient, args, out) -> int:
    """kubectl port-forward analog: local listener -> apiserver
    portforward subresource -> kubelet -> container app, raw TCP spliced
    end to end. Serves until interrupted (or ``--one-shot`` for one
    connection, which tests use)."""
    import socket as _socket
    import threading
    from urllib.parse import urlsplit
    local = int(args.ports.split(":")[0])
    parts = urlsplit(args.server)
    api = (parts.hostname, parts.port or 80)
    path = (f"/api/v1/namespaces/{args.namespace}/pods/"
            f"{args.name}/portforward")

    auth = (f"Authorization: Bearer {args.token}\r\n"
            if getattr(args, "token", None) else "")

    def handle(conn):
        from kubernetes_tpu.kubelet.server import upgrade_and_splice
        with conn:
            upgrade_and_splice(conn, api, path, extra_headers=auth)

    srv = _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM)
    srv.setsockopt(_socket.SOL_SOCKET, _socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", local))
    srv.listen(4)
    bound = srv.getsockname()[1]
    out.write(f"Forwarding from 127.0.0.1:{bound} -> pod {args.name}\n")
    try:
        while True:
            conn, _ = srv.accept()
            if args.one_shot:
                handle(conn)
                return 0
            threading.Thread(target=handle, args=(conn,),
                             daemon=True).start()
    except KeyboardInterrupt:
        return 0
    finally:
        srv.close()


def cmd_top(client: HTTPClient, args, out) -> int:
    """kubectl top analog from the scheduler's resource view: per-node
    requested/allocatable (nodes) or per-pod requests (pods). Upstream
    reads metrics-server usage; the hollow runtime has no real usage, so
    requests — the quantity every scheduling decision is made on — are
    the faithful figure here."""
    from kubernetes_tpu.api.resource import canonical
    pods = client.resource("pods", None).list()
    if args.resource == "pods":
        out.write(f"{'NAMESPACE':<16}{'NAME':<32}{'CPU':>10}{'MEMORY':>12}\n")
        for p in pods:
            md = p.get("metadata") or {}
            if args.namespace not in ("", md.get("namespace", "default")) \
                    and not args.all_namespaces:
                continue
            cpu = mem = 0
            for c in (p.get("spec") or {}).get("containers") or []:
                req = (c.get("resources") or {}).get("requests") or {}
                cpu += canonical("cpu", str(req.get("cpu", "0")))
                mem += canonical("memory", str(req.get("memory", "0")))
            out.write(f"{md.get('namespace', 'default'):<16}"
                      f"{md.get('name', ''):<32}"
                      f"{cpu}m{'':>4}{mem >> 20}Mi\n")
        return 0
    nodes = client.nodes().list()
    by_node: dict = {}
    for p in pods:
        nn = (p.get("spec") or {}).get("nodeName", "")
        if not nn:
            continue
        cpu = mem = 0
        for c in (p.get("spec") or {}).get("containers") or []:
            req = (c.get("resources") or {}).get("requests") or {}
            cpu += canonical("cpu", str(req.get("cpu", "0")))
            mem += canonical("memory", str(req.get("memory", "0")))
        acc = by_node.setdefault(nn, [0, 0])
        acc[0] += cpu
        acc[1] += mem
    out.write(f"{'NAME':<24}{'CPU(req)':>12}{'CPU%':>7}"
              f"{'MEM(req)':>12}{'MEM%':>7}\n")
    for n in nodes:
        name = (n.get("metadata") or {}).get("name", "")
        alloc = (n.get("status") or {}).get("allocatable") or {}
        acpu = canonical("cpu", str(alloc.get("cpu", "0"))) or 1
        amem = canonical("memory", str(alloc.get("memory", "0"))) or 1
        cpu, mem = by_node.get(name, [0, 0])
        out.write(f"{name:<24}{cpu}m{'':>6}{100 * cpu // acpu:>5}%"
                  f"{mem >> 20}Mi{'':>6}{100 * mem // amem:>5}%\n")
    return 0


def _kv_edits(pairs: list) -> tuple[dict, list]:
    """['k=v', 'gone-'] -> ({k: v}, [gone]) — kubectl label/annotate
    syntax (trailing '-' removes)."""
    sets, removes = {}, []
    for p in pairs:
        if p.endswith("-") and "=" not in p:
            removes.append(p[:-1])
        elif "=" in p:
            k, _, v = p.partition("=")
            sets[k] = v
        else:
            raise SystemExit(f"invalid pair {p!r} (want k=v or k-)")
    return sets, removes


def cmd_label(client: HTTPClient, args, out, field: str = "labels") -> int:
    """kubectl label/annotate: read-modify-write with the rv precondition
    (--overwrite required to change an existing key, like kubectl)."""
    plural = resolve_plural(args.resource, client)
    res = client.resource(plural, args.namespace)
    obj = res.get(args.name)
    sets, removes = _kv_edits(args.pairs)
    md = obj.setdefault("metadata", {})
    cur = md.setdefault(field, {})
    if not args.overwrite:
        clashes = [k for k, v in sets.items()
                   if k in cur and cur[k] != v]
        if clashes:
            out.write(f"error: {clashes[0]!r} already has a value; "
                      "use --overwrite\n")
            return 1
    cur.update(sets)
    for k in removes:
        cur.pop(k, None)
    res.update(obj)
    kind, _ns = _kind_info(client, plural)
    verb = "labeled" if field == "labels" else "annotated"
    out.write(f"{kind.lower()}/{args.name} {verb}\n")
    return 0


def cmd_wait(client: HTTPClient, args, out) -> int:
    """kubectl wait --for=condition=X / --for=delete / --for=jsonpath-free
    phase matching, polling until the condition holds or --timeout."""
    import time as _time
    plural = resolve_plural(args.resource, client)
    res = client.resource(plural, args.namespace)
    kind_lower = _kind_info(client, plural)[0].lower()
    want = args.wait_for
    if want != "delete" and not want.startswith(("condition=", "phase=")):
        out.write(f"error: unsupported --for {want!r} "
                  "(want condition=Type[=Status], phase=X, or delete)\n")
        return 2
    deadline = _time.time() + args.timeout
    while _time.time() < deadline:
        try:
            obj = res.get(args.name)
        except ApiError as e:
            if e.code == 404:
                if want == "delete":
                    out.write(f"{kind_lower}/{args.name} condition met\n")
                    return 0
                _time.sleep(args.poll)
                continue
            raise
        if want == "delete":
            _time.sleep(args.poll)
            continue
        if want.startswith("condition="):
            parts = want[len("condition="):].split("=", 1)
            ctype = parts[0]
            cstatus = parts[1] if len(parts) > 1 else "True"
            conds = (obj.get("status") or {}).get("conditions") or []
            if any(c.get("type", "").lower() == ctype.lower()
                   and str(c.get("status", "")).lower() == cstatus.lower()
                   for c in conds):
                out.write(f"{kind_lower}/{args.name} condition met\n")
                return 0
        elif want.startswith("phase="):
            if (obj.get("status") or {}).get("phase", "").lower() \
                    == want[len("phase="):].lower():
                out.write(f"{kind_lower}/{args.name} condition met\n")
                return 0
        _time.sleep(args.poll)
    out.write(f"error: timed out waiting for {want} on "
              f"{kind_lower}/{args.name}\n")
    return 1


def cmd_api_resources(client: HTTPClient, args, out) -> int:
    """kubectl api-resources: the serving table, CRDs included."""
    from kubernetes_tpu.store.apiserver import ALL_RESOURCES
    out.write(f"{'NAME':<36}{'KIND':<34}{'NAMESPACED':<10}\n")
    rows = sorted(ALL_RESOURCES.items())
    try:
        client.discover_custom()
        custom = getattr(client, "_custom", {}) or {}
        rows += sorted((p, info) for p, info in custom.items()
                       if p not in ALL_RESOURCES)
    except Exception:  # ktpu-lint: disable=KTL002 -- CLI api-resources augmentation: CRD listing is absent on older servers; the builtin table still prints
        pass
    for plural, info in rows:
        kind, namespaced = info[0], info[1]
        out.write(f"{plural:<36}{kind:<34}"
                  f"{str(bool(namespaced)).lower():<10}\n")
    return 0


def _fleet_line(fleet: dict) -> str:
    """One-line hollow-fleet summary from the fleet status ConfigMap."""
    hb = fleet.get("heartbeat") or {}
    le = fleet.get("lease") or {}
    return (f"Fleet:         {fleet.get('nodes', 0)} hollow nodes, "
            f"{fleet.get('shards', '?')} batcher shards — "
            f"heartbeats {hb.get('itemsPerS', 0)}/s "
            f"(batch {hb.get('lastBatch', 0)}), "
            f"leases {le.get('itemsPerS', 0)}/s "
            f"(batch {le.get('lastBatch', 0)})\n")


def _fleet_sched_line(fs: dict) -> str:
    """One-line fleet-scheduler summary (sched/fleet.py FleetRunner's
    per-tenant fairness ConfigMap): tenants, per-tenant pending/bound and
    the batch-slot share each got from the shared drain pipeline."""
    tenants = fs.get("tenant") or {}
    parts = []
    for t in sorted(tenants, key=lambda s: (len(s), s)):
        d = tenants[t] or {}
        parts.append(f"t{t} {d.get('bound', 0)} bound/"
                     f"{d.get('pending', 0)} pending/"
                     f"share {d.get('batchShare', 0)}")
    return (f"Fleet sched:   {fs.get('tenants', 0)} tenants, one warm "
            f"program — " + ("; ".join(parts) if parts else "no tenants")
            + "\n")


def _durability_line(dur: dict) -> str:
    """One-line apiserver durability summary (data_dir mode): WAL growth
    since the last snapshot fold, snapshot age, what the last restore
    cost, and the readyz verdict."""
    import time as _time
    snap_ts = dur.get("lastSnapshotTime")
    age = (f"{max(0.0, _time.time() - float(snap_ts)):.0f}s ago"
           if snap_ts else "never")
    replay = dur.get("replayMs")
    torn = dur.get("tornTailsDropped") or 0
    return (f"Durability:    WAL {dur.get('walEntriesSinceSnapshot', 0)} "
            f"entries since snapshot ({age}), last replay "
            f"{replay if replay is not None else '?'}ms"
            f" ({dur.get('walEntriesReplayed', 0)} entries"
            + (f", {torn} torn tail dropped" if torn else "")
            + f"), readyz {'ok' if dur.get('ready') else 'NOT READY'}\n")


def _disruption_line(dis: dict) -> str:
    """One-line node-lifecycle disruption-mode summary."""
    mode = dis.get("mode", "Normal")
    frac = dis.get("unreadyFraction", 0.0)
    extra = ""
    if mode != "Normal":
        extra = (" — EVICTIONS "
                 + ("HALTED" if dis.get("evictionsHalted")
                    else "at secondary rate"))
    return (f"Disruption:    {mode} "
            f"({frac:.0%} of {dis.get('nodes', 0)} nodes unready; "
            f"engaged {dis.get('engagedCount', 0)}x, "
            f"evictions {dis.get('evictions', 0)}, "
            f"deferred {dis.get('evictionsDeferred', 0)}, "
            f"taints suppressed {dis.get('taintsSuppressed', 0)})"
            f"{extra}\n")


def _aot_cache_line(ac: dict) -> str:
    """One-line durable compile-cache summary (sched/aotcache.py stats):
    what's on disk, how this boot used it, and whether anything had to be
    swept or recompiled."""
    if not ac.get("enabled"):
        return "Compile cache: off (no aotCacheDir configured)\n"
    if ac.get("error"):
        return f"Compile cache: on — {ac['error']}\n"
    mb = (ac.get("bytes") or 0) / 1e6
    boot_ms = ac.get("bootLoadMs")
    return (f"Compile cache: {ac.get('entries', 0)} entries "
            f"({mb:.1f} MB) — boot loaded {ac.get('bootEntries', 0)} in "
            f"{boot_ms if boot_ms is not None else '?'}ms, "
            f"hits {ac.get('hits', 0)}, misses {ac.get('misses', 0)}, "
            f"errors {ac.get('errors', 0)}, "
            f"invalidations {ac.get('invalidations', 0)}\n")


def _topology_line(topo: dict) -> str:
    """One-line slice-carving summary (scheduler.topology_status): the ICI
    grid extent, per-requested-shape carveability + fragmentation, and the
    carve counters."""
    shapes = topo.get("shapes") or {}
    parts = []
    for s, cov in sorted(shapes.items()):
        frag = cov.get("fragmentationPct")
        parts.append(f"{s}: {cov.get('origins', 0)} carveable"
                     + (f", {frag}% fragmented" if frag is not None else ""))
    carves = topo.get("carves") or {}
    return (f"Topology:      {topo.get('grid', '?')} grid "
            f"({topo.get('nodes', 0)} nodes, "
            f"{topo.get('freeCells', 0)} free cells)"
            + (" — " + "; ".join(parts) if parts else "")
            + (f" — carves {carves.get('carved', 0)} ok / "
               f"{carves.get('failed', 0)} failed / "
               f"{carves.get('slicePreempts', 0)} slice-preempts"
               if carves else "")
            + "\n")


def _frontdoor_line(fd: dict) -> str:
    """One-line read-replica serving-plane summary (the front-door
    publisher's ConfigMap): who leads, how many replicas serve reads,
    watcher spread, worst replay lag, and slow-consumer drops."""
    nodes = fd.get("nodes") or []
    reachable = sum(1 for n in nodes if n.get("reachable"))
    return (f"Front door:    leader {fd.get('leader') or '<unknown>'} + "
            f"{fd.get('replicas', '0')} read replicas "
            f"({reachable}/{len(nodes)} reachable) — "
            f"{fd.get('watchersTotal', '0')} watchers over "
            f"{fd.get('shardsPerKind', '0')} shards/kind, "
            f"max replay lag {fd.get('maxReplayLagMs', '0')}ms, "
            f"drops {fd.get('dropsTotal', '0')}\n")


def _scenario_line(sc: dict) -> str:
    """One-line scenario-driver digest from the
    ``kubernetes-tpu-scenario-status`` ConfigMap."""
    return (f"Scenario:      {sc.get('trace', '<unnamed>')} "
            f"{sc.get('state', '?')}"
            + (f" (phase {sc['phase']})" if sc.get("phase") else "")
            + f" — {sc.get('eventsDispatched', 0)}/"
              f"{sc.get('eventsTotal', 0)} events, "
              f"{sc.get('podsBound', 0)}/{sc.get('podsResident', 0)} "
              f"bound, skew max {sc.get('skewMaxMs', 0)}ms, "
              f"speed {sc.get('speed', 1.0)}x\n")


def _planner_line(pl: dict) -> str:
    """One-line background-planner digest from the
    ``kubernetes-tpu-planner-status`` ConfigMap: per-planner overlay
    hit/decline counts plus the steady-window compile total."""
    planners = pl.get("planners") or {}
    parts = []
    for name in ("autoscaler", "descheduler", "gangDefrag"):
        p = planners.get(name) or {}
        parts.append(f"{name} {p.get('hits', 0)}/{p.get('declines', 0)}")
    interval = pl.get("intervalSeconds")
    return (f"Planners:      {pl.get('cycles', 0)} cycles"
            + (f" @ {interval}s" if interval is not None else "")
            + f" — hits/declines: {', '.join(parts)} — "
              f"steady compiles {pl.get('steadyCompiles', 0)}\n")


def cmd_status(client: HTTPClient, args, out) -> int:
    """ktpu status: the connected scheduler's published deployment shape
    (the ``kubernetes-tpu-scheduler-status`` ConfigMap) — most importantly
    the active device mesh the drain/dispatch path runs under."""
    from kubernetes_tpu.controllers.nodelifecycle import (
        NODELIFECYCLE_CONFIGMAP)
    from kubernetes_tpu.kubelet.kubemark import FLEET_CONFIGMAP
    from kubernetes_tpu.sched.runner import STATUS_CONFIGMAP
    from kubernetes_tpu.store.apiserver import APISERVER_CONFIGMAP

    def _aux_cm(name: str, key: str):
        # sibling status ConfigMaps (fleet / apiserver durability /
        # nodelifecycle disruption); absent when that component isn't
        # running against this apiserver
        try:
            cm_ = client.resource("configmaps", args.namespace).get(name)
            return json.loads((cm_.get("data") or {}).get(key, "{}")
                              or "{}")
        except ApiError as e:
            if e.code != 404:
                raise
            return None

    def _frontdoor_cm():
        # the front-door ConfigMap is flat str->str (scalar summary keys
        # + a JSON "nodes" list), published to kube-system by default
        from kubernetes_tpu.store.frontdoor import (FRONTDOOR_CONFIGMAP,
                                                    FRONTDOOR_NAMESPACE)
        for ns_ in dict.fromkeys((FRONTDOOR_NAMESPACE, args.namespace)):
            try:
                cm_ = client.resource("configmaps",
                                      ns_).get(FRONTDOOR_CONFIGMAP)
            except ApiError as e:
                if e.code != 404:
                    raise
                continue
            data = dict(cm_.get("data") or {})
            try:
                data["nodes"] = json.loads(data.get("nodes", "[]") or "[]")
            except json.JSONDecodeError:
                data["nodes"] = []
            return data
        return None

    from kubernetes_tpu.scenario.driver import SCENARIO_CONFIGMAP
    from kubernetes_tpu.sched.bgplanner import PLANNER_CONFIGMAP
    from kubernetes_tpu.sched.fleet import FLEET_SCHED_CONFIGMAP
    fleet = _aux_cm(FLEET_CONFIGMAP, "fleet")
    fleet_sched = _aux_cm(FLEET_SCHED_CONFIGMAP, "fleetSched")
    durability = _aux_cm(APISERVER_CONFIGMAP, "durability")
    disruption = _aux_cm(NODELIFECYCLE_CONFIGMAP, "disruption")
    scenario = _aux_cm(SCENARIO_CONFIGMAP, "scenario")
    planner = _aux_cm(PLANNER_CONFIGMAP, "status")
    frontdoor = _frontdoor_cm()
    try:
        cm = client.resource("configmaps", args.namespace).get(
            STATUS_CONFIGMAP)
    except ApiError as e:
        if e.code != 404:
            raise
        aux = {k: v for k, v in (("fleet", fleet),
                                 ("fleetSched", fleet_sched),
                                 ("durability", durability),
                                 ("disruption", disruption),
                                 ("scenario", scenario),
                                 ("planner", planner),
                                 ("frontdoor", frontdoor))
               if v is not None}
        if aux:
            # a fleet/durable-apiserver/lifecycle-controller without a
            # scheduler is still worth reporting
            if args.output == "json":
                out.write(json.dumps(aux) + "\n")
            else:
                if frontdoor is not None:
                    out.write(_frontdoor_line(frontdoor))
                if durability is not None:
                    out.write(_durability_line(durability))
                if disruption is not None:
                    out.write(_disruption_line(disruption))
                if fleet is not None:
                    out.write(_fleet_line(fleet))
                if fleet_sched is not None:
                    out.write(_fleet_sched_line(fleet_sched))
                if scenario is not None:
                    out.write(_scenario_line(scenario))
                if planner is not None:
                    out.write(_planner_line(planner))
            return 0
        out.write("error: no scheduler status published "
                  f"(configmap {STATUS_CONFIGMAP!r} not found in "
                  f"{args.namespace!r})\n")
        return 1
    data = cm.get("data") or {}
    if args.output == "json":
        st = json.loads(data.get("status", "{}") or "{}")
        if fleet is not None:
            st["fleet"] = fleet
        if fleet_sched is not None:
            st["fleetSched"] = fleet_sched
        if durability is not None:
            st["durability"] = durability
        if disruption is not None:
            st["disruption"] = disruption
        if scenario is not None:
            st["scenario"] = scenario
        if planner is not None:
            st["planner"] = planner
        if frontdoor is not None:
            st["frontdoor"] = frontdoor
        out.write(json.dumps(st) + "\n")
        return 0
    st = json.loads(data.get("status", "{}") or "{}")
    mesh = st.get("mesh")
    if mesh:
        shape = mesh.get("shape") or {}
        dims = "x".join(str(shape[a]) for a in ("pods", "nodes")
                        if a in shape) or "?"
        out.write(f"Mesh:          {dims} ({mesh.get('devices', '?')} "
                  "devices, pods x nodes)\n")
        out.write(f"Device ids:    {mesh.get('deviceIds')}\n")
    else:
        out.write("Mesh:          off (single-device)\n")
    out.write(f"Identity:      {st.get('identity', '<unknown>')}\n")
    out.write(f"Batch size:    {st.get('batchSize', '?')}\n")
    out.write(f"Drain batches: {st.get('maxDrainBatches', '?')}\n")
    inflight = st.get("pipelineInflight")
    out.write(f"Pipeline:      {st.get('pipelineDepth', '?')} deep"
              + (f" ({inflight} in flight)" if inflight is not None else "")
              + "\n")
    ctx = st.get("ctx")
    if ctx is not None:
        fused = st.get("fusedFold")
        reasons = ", ".join(f"{k}={v}" for k, v in
                            sorted((ctx.get("reasons") or {}).items()))
        out.write(f"Resident ctx:  folds {ctx.get('folds', 0)}, "
                  f"patches {ctx.get('patches', 0)}, "
                  f"rebuilds {ctx.get('rebuilds', 0)}"
                  + (f" ({reasons})" if reasons else "")
                  + (f" — fused fold {'on' if fused else 'off'}"
                     if fused is not None else "")
                  + "\n")
    staging = st.get("staging")
    if staging is not None:
        mb = (staging.get("bytesStaged") or 0) / 1e6
        out.write(f"Staging:       arena "
                  f"{'on' if staging.get('enabled') else 'off'} — "
                  f"{staging.get('swaps', 0)} swaps, "
                  f"{staging.get('fallbacks', 0)} fallbacks, "
                  f"{mb:.1f} MB pre-staged\n")
    out.write(f"Profiles:      {', '.join(st.get('profiles') or [])}\n")
    pending = st.get("pending")
    if pending is not None:
        out.write(f"Pending pods:  active {pending.get('active', 0)}, "
                  f"backoff {pending.get('backoff', 0)}, "
                  f"unschedulable {pending.get('unschedulable', 0)}\n")
    e2e = st.get("e2e")
    if e2e and e2e.get("count"):
        out.write(f"E2E latency:   p50 {e2e.get('p50Seconds')}s, "
                  f"p99 {e2e.get('p99Seconds')}s "
                  f"({e2e.get('count')} pods)\n")
    explain = st.get("explain")
    if explain is not None:
        out.write(f"Explainer:     {explain.get('podsExplained', 0)} pods "
                  f"explained ({explain.get('entries', 0)} live, "
                  f"skipped {explain.get('skipped', 0)}, "
                  f"errors {explain.get('errors', 0)}) — ktpu why <pod>\n")
    flight = st.get("flight")
    if flight is not None:
        out.write(f"Flight rec:    "
                  f"{'on' if flight.get('enabled') else 'off'} "
                  f"({flight.get('pods', 0)} pod timelines, "
                  f"dropped {flight.get('droppedPods', 0)}) — "
                  "ktpu trace dump\n")
    aot = st.get("aotCache")
    if aot is not None:
        out.write(_aot_cache_line(aot))
    topo = st.get("topology")
    if topo is not None:
        out.write(_topology_line(topo))
    if frontdoor is not None:
        out.write(_frontdoor_line(frontdoor))
    if durability is not None:
        out.write(_durability_line(durability))
    if disruption is not None:
        out.write(_disruption_line(disruption))
    if fleet is not None:
        out.write(_fleet_line(fleet))
    if fleet_sched is not None:
        out.write(_fleet_sched_line(fleet_sched))
    if scenario is not None:
        out.write(_scenario_line(scenario))
    if planner is not None:
        out.write(_planner_line(planner))
    res = st.get("resilience")
    if res:
        degraded = (res.get("degradedIndex") or 0) > 0
        out.write(f"Degraded:      "
                  f"{res.get('degradedMode') if degraded else 'no'} "
                  f"(breaker trips: {res.get('breakerTrips', 0)}, "
                  f"restores: {res.get('breakerRestores', 0)})\n")
        out.write(f"Watchdog:      "
                  f"{res.get('watchdogRestarts', 0)} restarts\n")
        out.write(f"Last relist:   "
                  f"{res.get('lastRelist') or 'never'} "
                  f"(relists: {res.get('watchRelists', 0)})\n")
    return 0


def cmd_why(client: HTTPClient, args, out) -> int:
    """ktpu why <pod>: per-pod decision provenance. A bound pod reports
    its node (+ the Scheduled event); a pending pod gets the explainer's
    per-filter reject breakdown from the ``scheduler-explanations``
    ConfigMap — the upstream-style "0/N nodes are available: ..." verdict
    with the node count each filter rejected."""
    from kubernetes_tpu.sched.runner import EXPLAIN_CONFIGMAP
    key = f"{args.namespace}/{args.name}"
    pod = None
    try:
        pod = client.pods(args.namespace).get(args.name)
    except ApiError as e:
        if e.code != 404:
            raise
    if pod is not None and (pod.get("spec") or {}).get("nodeName"):
        node = pod["spec"]["nodeName"]
        if args.output == "json":
            out.write(json.dumps({"pod": key, "scheduled": True,
                                  "node": node}) + "\n")
            return 0
        out.write(f"pod {key}: scheduled to {node}\n")
        from kubernetes_tpu.utils.events import events_for
        for e in events_for(client, args.namespace, args.name,
                            uid=(pod.get("metadata") or {}).get("uid")):
            if e.get("reason") == "Scheduled":
                out.write(f"  {e.get('message')}\n")
        return 0
    # the ConfigMap lives in the RUNNER's status namespace, not the pod's:
    # try the pod's namespace first (single-namespace deployments), then
    # the runner default — explanations are keyed ns/name, so a pod from
    # any namespace resolves once the right ConfigMap is found
    explanation = None
    for cm_ns in dict.fromkeys((args.namespace, "default")):
        try:
            cm = client.resource("configmaps", cm_ns).get(EXPLAIN_CONFIGMAP)
        except ApiError as e:
            if e.code != 404:
                raise
            continue
        explanation = json.loads(
            (cm.get("data") or {}).get("explanations", "{}")).get(key)
        if explanation is not None:
            break
    if pod is None and explanation is None:
        out.write(f"error: pod {key} not found\n")
        return 1
    if explanation is None:
        out.write(f"pod {key}: pending, no explanation recorded yet "
                  "(the explainer publishes after the pod's first failed "
                  "cycle; is explainerEnabled on?)\n")
        return 1
    if args.output == "json":
        out.write(json.dumps({"pod": key, "scheduled": False,
                              **explanation}, indent=1) + "\n")
        return 0
    out.write(f"pod {key}: unschedulable ({explanation.get('mode')} "
              f"verdict at {explanation.get('ts')})\n")
    out.write(f"  {explanation.get('message')}\n")
    filters = explanation.get("filters") or {}
    for f, c in sorted(filters.items(), key=lambda kv: -kv[1]):
        out.write(f"    {f}: {c} node(s)\n")
    if explanation.get("feasibleNow"):
        out.write(f"  note: {explanation['feasibleNow']} node(s) were "
                  "feasible when re-judged — retry may succeed\n")
    return 0


def cmd_scenario(client, args, out) -> int:
    """ktpu scenario generate|record|replay|describe: the cluster time
    machine. generate/record/describe are local file operations (no
    apiserver — main() dispatches them before building a client); replay
    drives the trace against the connected apiserver/scheduler stack."""
    from kubernetes_tpu.scenario import (BUILTINS, ScenarioDriver, Trace,
                                         TraceFormatError, builtin_trace,
                                         trace_from_bundle, trace_from_wal)

    def _resolve(spec: str) -> Trace:
        if spec.startswith("builtin:"):
            return builtin_trace(spec[len("builtin:"):], seed=args.seed)
        if spec in BUILTINS:  # bare builtin name is unambiguous enough
            return builtin_trace(spec, seed=args.seed)
        return Trace.load(spec)

    try:
        if args.action == "generate":
            if not args.target:
                out.write("error: generate needs a builtin name "
                          f"(catalog: {', '.join(sorted(BUILTINS))})\n")
                return 1
            trace = _resolve(args.target)
            path = args.out_path or f"{trace.manifest.name}.trace.jsonl"
            trace.save(path)
            out.write(f"wrote {len(trace)} events to {path}\n")
            out.write(json.dumps(trace.describe(), indent=1) + "\n")
            return 0
        if args.action == "record":
            if bool(args.from_wal) == bool(args.from_bundle):
                out.write("error: record needs exactly one of "
                          "--from-wal WAL.jsonl / "
                          "--from-bundle BUNDLE.json\n")
                return 1
            if args.from_wal:
                trace = trace_from_wal(args.from_wal,
                                       chaos_seed=args.chaos_seed)
            else:
                trace = trace_from_bundle(args.from_bundle)
            path = args.out_path or f"{trace.manifest.name}.trace.jsonl"
            trace.save(path)
            out.write(f"captured {len(trace)} events to {path}\n")
            out.write(json.dumps(trace.describe(), indent=1) + "\n")
            return 0
        if args.action == "describe":
            if not args.target:
                out.write("error: describe needs a trace path or "
                          "builtin:<name>\n")
                return 1
            out.write(json.dumps(_resolve(args.target).describe(),
                                 indent=1) + "\n")
            return 0
        # replay: the live path — client is a real HTTPClient here
        if not args.target:
            out.write("error: replay needs a trace path or "
                      "builtin:<name>\n")
            return 1
        trace = _resolve(args.target)
        driver = ScenarioDriver(client, trace, speed=args.speed,
                                status_namespace=args.namespace,
                                bind_timeout_s=args.bind_timeout)
        result = driver.run()
        out.write(json.dumps(result, indent=1) + "\n")
        return 0 if result["completed"] else 1
    except (TraceFormatError, KeyError, OSError) as e:
        out.write(f"error: {e}\n")
        return 1


def cmd_trace(client: HTTPClient, args, out) -> int:
    """ktpu trace dump: the scheduler's flight-recorder export (batch
    spans + per-pod lifecycle tracks) as Chrome trace-event JSON — load
    the output in https://ui.perfetto.dev or chrome://tracing."""
    from kubernetes_tpu.sched.runner import TRACE_CONFIGMAP
    from kubernetes_tpu.utils.tracing import validate_chrome_trace
    try:
        cm = client.resource("configmaps", args.namespace).get(
            TRACE_CONFIGMAP)
    except ApiError as e:
        if e.code != 404:
            raise
        out.write("error: no trace published "
                  f"(configmap {TRACE_CONFIGMAP!r} not found in "
                  f"{args.namespace!r})\n")
        return 1
    raw = (cm.get("data") or {}).get("trace", "")
    try:
        doc = json.loads(raw or "{}")
    except ValueError:
        out.write("error: published trace is not valid JSON\n")
        return 1
    problems = validate_chrome_trace(doc)
    if problems:
        out.write("error: published trace fails the Chrome trace-event "
                  f"schema: {problems[0]} (+{len(problems) - 1} more)\n")
        return 1
    if args.output_file:
        with open(args.output_file, "w") as f:
            f.write(raw)
        out.write(f"wrote {len(doc.get('traceEvents', []))} events to "
                  f"{args.output_file} (load in ui.perfetto.dev)\n")
    else:
        out.write(raw + "\n")
    return 0


def cmd_audit(client: HTTPClient, args, out) -> int:
    """ktpu audit status: the continuous invariant auditor's published
    state (the ``audit`` block of the scheduler status ConfigMap) —
    invariants checked, confirmed violations, repro-bundle locations, and
    the device-parity sentinel's sample/divergence counters."""
    from kubernetes_tpu.sched.runner import STATUS_CONFIGMAP
    try:
        cm = client.resource("configmaps", args.namespace).get(
            STATUS_CONFIGMAP)
    except ApiError as e:
        if e.code != 404:
            raise
        out.write("error: no scheduler status published "
                  f"(configmap {STATUS_CONFIGMAP!r} not found in "
                  f"{args.namespace!r})\n")
        return 1
    st = json.loads((cm.get("data") or {}).get("status", "{}") or "{}")
    audit = st.get("audit")
    if audit is None:
        out.write("error: scheduler status carries no audit block "
                  "(older scheduler?)\n")
        return 1
    if args.output == "json":
        out.write(json.dumps(audit, indent=1) + "\n")
        return 0
    out.write(f"Sweeps:        {audit.get('sweeps', 0)} "
              f"(every {audit.get('intervalSeconds', '?')}s, "
              f"last: {audit.get('lastSweep') or 'never'})\n")
    out.write(f"Fail-fast:     "
              f"{'on' if audit.get('failFast') else 'off'}"
              f"{' — TRIPPED' if audit.get('failed') else ''}\n")
    n = audit.get("violations", 0)
    out.write(f"Violations:    {n}\n")
    for inv, c in sorted((audit.get("byInvariant") or {}).items()):
        out.write(f"  {inv}: {c}\n")
    out.write(f"Bundles:       {audit.get('bundleDir')}\n")
    for b in audit.get("bundles") or []:
        out.write(f"  {b}\n")
    par = audit.get("parity")
    if par:
        samples = par.get("samples") or {}
        out.write(f"Parity:        every {par.get('every')}th dispatch "
                  f"(drain samples: {samples.get('drain', 0)}, "
                  f"wave: {samples.get('wave', 0)}, "
                  f"skipped: {par.get('skipped', 0)})\n")
        out.write(f"Divergences:   {par.get('divergences', 0)}\n")
        last = par.get("lastDivergence")
        if last:
            out.write(f"  last: {last.get('site')} at level "
                      f"{last.get('level')} -> {last.get('mode')} "
                      f"(bundle: {last.get('bundle')})\n")
    else:
        out.write("Parity:        off\n")
    return 0


def cmd_autoscale(client: HTTPClient, args, out) -> int:
    """ktpu autoscale status: the cluster-autoscaler's published status
    (the ``cluster-autoscaler-status`` ConfigMap, same surface as the
    reference autoscaler's kube-system ConfigMap)."""
    from kubernetes_tpu.autoscaler import STATUS_CONFIGMAP
    try:
        cm = client.resource("configmaps", args.namespace).get(
            STATUS_CONFIGMAP)
    except ApiError as e:
        if e.code != 404:
            raise
        out.write("error: no autoscaler status published "
                  f"(configmap {STATUS_CONFIGMAP!r} not found in "
                  f"{args.namespace!r})\n")
        return 1
    data = cm.get("data") or {}
    if args.output == "json":
        out.write(data.get("status", "{}") + "\n")
        return 0
    st = json.loads(data.get("status", "{}") or "{}")
    out.write(f"Last probe:   {data.get('lastProbeTime', '<unknown>')}\n")
    out.write(f"Expander:     {st.get('expander', '<unknown>')}\n")
    groups = st.get("groups") or {}
    if groups:
        out.write(f"{'GROUP':<24}{'SIZE':>6}{'MIN':>6}{'MAX':>6}  STATE\n")
        for name in sorted(groups):
            g = groups[name]
            state = ("backoff" if g.get("backoff")
                     else "cooldown" if g.get("cooldown") else "ready")
            out.write(f"{name:<24}{g.get('size', 0):>6}"
                      f"{g.get('minSize', 0):>6}{g.get('maxSize', 0):>6}"
                      f"  {state}\n")
    for verb, key in (("scale-up", "lastScaleUp"),
                      ("scale-down", "lastScaleDown")):
        ev = st.get(key)
        if ev:
            what = ",".join(ev.get("nodes", [])) or ev.get("node", "")
            out.write(f"Last {verb}: group={ev.get('group')} "
                      f"nodes={what} at={ev.get('at')}\n")
    return 0


def cmd_deschedule(client: HTTPClient, args, out) -> int:
    """ktpu deschedule run|status: drive one descheduler cycle in-process
    (run) or read the loop's published ``descheduler-status`` ConfigMap
    (status) — same surface split as ``autoscale status``."""
    from kubernetes_tpu.descheduler import (
        STATUS_CONFIGMAP as DESCHED_CM,
        Descheduler,
        DeschedulerConfiguration,
    )
    if args.action == "run":
        cfg = (DeschedulerConfiguration.from_yaml(args.policy)
               if args.policy else DeschedulerConfiguration())
        if args.max_evictions is not None:
            cfg.max_evictions_per_cycle = args.max_evictions
        summary = Descheduler(client, cfg).run_once(dry_run=args.dry_run)
        if args.output == "json":
            out.write(json.dumps(summary, indent=1) + "\n")
            return 0
        verb = "would evict" if args.dry_run else "evicted"
        for s in summary["planned"]:
            out.write(f"{s['strategy']}: {s['set']} -> "
                      f"{s['evictions']} eviction(s)\n")
            for key, target in s["moves"]:
                out.write(f"  {key} -> {target}\n")
        for g in summary["gangs"]:
            state = ("fits without evictions" if g["fitsWithoutEvictions"]
                     else f"{g['evictions']} eviction(s) via {g['set']}"
                     if g["set"] else "no feasible consolidation")
            out.write(f"gang {g['gang']}: {state}\n")
        for name, why in sorted(summary["blocked"].items()):
            out.write(f"blocked {name}: {why}\n")
        if args.dry_run:
            # planned totals include gang-defrag victims, matching what a
            # wet run's `evicted` list would contain for the same plan
            n = (sum(s["evictions"] for s in summary["planned"])
                 + sum(g["evictions"] for g in summary["gangs"]))
        else:
            n = len(summary.get("evicted", []))
        out.write(f"{verb} {n} pod(s)\n")
        return 0
    # status
    try:
        cm = client.resource("configmaps", args.namespace).get(DESCHED_CM)
    except ApiError as e:
        if e.code != 404:
            raise
        out.write("error: no descheduler status published "
                  f"(configmap {DESCHED_CM!r} not found in "
                  f"{args.namespace!r})\n")
        return 1
    data = cm.get("data") or {}
    if args.output == "json":
        out.write(data.get("status", "{}") + "\n")
        return 0
    st = json.loads(data.get("status", "{}") or "{}")
    out.write(f"Last probe:   {data.get('lastProbeTime', '<unknown>')}\n")
    out.write(f"Strategies:   {', '.join(st.get('strategies') or [])}\n")
    out.write(f"Gang defrag:  "
              f"{'on' if st.get('gangDefrag') else 'off'}\n")
    out.write(f"Max/cycle:    {st.get('maxEvictionsPerCycle')}\n")
    last = st.get("lastCycle") or {}
    if last:
        out.write(f"Last cycle:   planned={last.get('planned', 0)} "
                  f"evicted={last.get('evicted', 0)} at={last.get('at')}\n")
    loop = st.get("lastLoop") or {}
    for name, why in sorted((loop.get("blocked") or {}).items()):
        out.write(f"  blocked {name}: {why}\n")
    return 0


REVISION_ANNOTATION = "deployment.kubernetes.io/revision"


def cmd_rollout(client: HTTPClient, args, out) -> int:
    """kubectl rollout status|history|undo|restart for Deployments
    (kubectl/pkg/cmd/rollout; revisions ride the ReplicaSet revision
    annotation exactly like upstream)."""
    deps = client.resource("deployments", args.namespace)
    dep = deps.get(args.name)
    spec = dep.get("spec") or {}
    status = dep.get("status") or {}
    if args.action == "status":
        want = int(spec.get("replicas", 1))
        updated = int(status.get("updatedReplicas", 0) or 0)
        avail = int(status.get("availableReplicas",
                               status.get("readyReplicas", 0)) or 0)
        if updated >= want and avail >= want:
            out.write(f'deployment "{args.name}" successfully rolled out\n')
            return 0
        out.write(f"Waiting for deployment \"{args.name}\" rollout: "
                  f"{updated} of {want} updated, {avail} available\n")
        return 1
    rss = [rs for rs in client.resource("replicasets",
                                        args.namespace).list()
           if any(ref.get("kind") == "Deployment"
                  and ref.get("name") == args.name
                  for ref in (rs.get("metadata") or {})
                  .get("ownerReferences") or [])]
    rss.sort(key=lambda rs: int(((rs.get("metadata") or {})
                                 .get("annotations") or {})
                                .get(REVISION_ANNOTATION, "0") or 0))
    if args.action == "history":
        out.write(f"deployment.apps/{args.name}\nREVISION\n")
        for rs in rss:
            rev = ((rs.get("metadata") or {}).get("annotations") or {}) \
                .get(REVISION_ANNOTATION, "?")
            out.write(f"{rev}\n")
        return 0
    if args.action == "undo":
        if len(rss) < 2:
            out.write("error: no rollout history found\n")
            return 1
        prev = rss[-2]  # previous revision's template
        dep["spec"]["template"] = (prev.get("spec") or {}).get("template")
        deps.update(dep)
        out.write(f"deployment.apps/{args.name} rolled back\n")
        return 0
    if args.action == "restart":
        import datetime
        tmpl = dep["spec"].setdefault("template", {})
        md = tmpl.setdefault("metadata", {})
        md.setdefault("annotations", {})[
            "kubectl.kubernetes.io/restartedAt"] = \
            datetime.datetime.now(datetime.timezone.utc).isoformat()
        deps.update(dep)
        out.write(f"deployment.apps/{args.name} restarted\n")
        return 0
    return 2


# ------------------------------------------------------------------- main

def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="ktpu", description=__doc__.split("\n")[0])
    ap.add_argument("--server", "-s", default="http://127.0.0.1:8001")
    ap.add_argument("--token", default=None,
                    help="bearer token (rest.Config.BearerToken analog)")
    ap.add_argument("--namespace", "-n", default="default")
    sub = ap.add_subparsers(dest="cmd", required=True)

    g = sub.add_parser("get")
    g.add_argument("resource")
    g.add_argument("name", nargs="?", default="")
    g.add_argument("-o", "--output", choices=["table", "wide", "json", "yaml"],
                   default="table")
    g.add_argument("-l", "--selector", default=None)
    g.add_argument("-A", "--all-namespaces", action="store_true")

    a = sub.add_parser("apply")
    a.add_argument("-f", "--filename", required=True)
    a.add_argument("--server-side", action="store_true",
                   help="server-side apply (managedFields field ownership)")
    a.add_argument("--field-manager", default="ktpu")
    a.add_argument("--force-conflicts", action="store_true")

    d = sub.add_parser("delete")
    d.add_argument("resource", nargs="?", default="")
    d.add_argument("name", nargs="?", default="")
    d.add_argument("-f", "--filename", default=None)
    d.add_argument("--cascade", default="background",
                   choices=["background", "foreground", "orphan"],
                   help="DeleteOptions.propagationPolicy")

    de = sub.add_parser("describe")
    de.add_argument("resource")
    de.add_argument("name")

    cert = sub.add_parser("certificate")
    cert.add_argument("action", choices=["approve", "deny"])
    cert.add_argument("name")

    sc = sub.add_parser("scale")
    sc.add_argument("resource")
    sc.add_argument("name")
    sc.add_argument("--replicas", type=int, required=True)

    for nm in ("cordon", "uncordon", "drain"):
        c = sub.add_parser(nm)
        c.add_argument("name")

    lg = sub.add_parser("logs")
    lg.add_argument("name")
    lg.add_argument("-c", "--container", default=None)

    ex = sub.add_parser("exec")
    ex.add_argument("name")
    ex.add_argument("-c", "--container", default=None)
    ex.add_argument("command", nargs=argparse.REMAINDER,
                    help="-- cmd args...")

    pf = sub.add_parser("port-forward")
    pf.add_argument("name")
    pf.add_argument("ports", help="local[:remote]")
    pf.add_argument("--one-shot", action="store_true",
                    help="serve a single connection then exit")

    for nm in ("label", "annotate"):
        lb = sub.add_parser(nm)
        lb.add_argument("resource")
        lb.add_argument("name")
        lb.add_argument("pairs", nargs="+", help="k=v ... or k- to remove")
        lb.add_argument("--overwrite", action="store_true")

    sub.add_parser("api-resources")

    wt = sub.add_parser("wait")
    wt.add_argument("resource")
    wt.add_argument("name")
    wt.add_argument("--for", dest="wait_for", required=True,
                    help="condition=Type[=Status] | phase=X | delete")
    wt.add_argument("--timeout", type=float, default=30.0)
    wt.add_argument("--poll", type=float, default=0.2)

    at = sub.add_parser("attach")  # kubectl attach ~ exec without command
    at.add_argument("name")
    at.add_argument("-c", "--container", default=None)

    tp = sub.add_parser("top")
    tp.add_argument("resource", choices=["nodes", "pods"])
    tp.add_argument("-A", "--all-namespaces", action="store_true")

    ro = sub.add_parser("rollout")
    ro.add_argument("action",
                    choices=["status", "history", "undo", "restart"])
    ro.add_argument("kind_name", help="deployment/<name>")

    st = sub.add_parser("status")
    st.add_argument("-o", "--output", choices=["table", "json"],
                    default="table")

    asc = sub.add_parser("autoscale")
    asc.add_argument("action", choices=["status"])
    asc.add_argument("-o", "--output", choices=["table", "json"],
                     default="table")

    au = sub.add_parser("audit")
    au.add_argument("action", choices=["status"])
    au.add_argument("-o", "--output", choices=["table", "json"],
                    default="table")

    wy = sub.add_parser("why", help="explain a pod's scheduling verdict")
    wy.add_argument("name")
    wy.add_argument("-o", "--output", choices=["table", "json"],
                    default="table")

    tr = sub.add_parser("trace")
    tr.add_argument("action", choices=["dump"])
    tr.add_argument("-o", "--output-file", default=None,
                    help="write the Chrome trace-event JSON here "
                    "(default: stdout)")

    lt = sub.add_parser(
        "lint", help="project-native static analysis (ktpu-lint)")
    # mirrors kubernetes_tpu.analysis.cli flags (REMAINDER can't forward
    # leading optionals); dispatch rebuilds the argv and hands off
    lt.add_argument("lint_paths", nargs="*")
    lt.add_argument("--baseline", default=None)
    lt.add_argument("--write-baseline", action="store_true")
    lt.add_argument("--no-baseline", action="store_true")
    lt.add_argument("--json", action="store_true", dest="lint_json")
    lt.add_argument("--rule", action="append", default=None)

    sn = sub.add_parser(
        "scenario", help="cluster time machine: generate, record, "
        "replay, and describe production-shaped traces")
    sn.add_argument("action",
                    choices=["generate", "record", "replay", "describe"])
    sn.add_argument("target", nargs="?", default=None,
                    help="builtin:<name> (or bare builtin name) or a "
                    ".trace.jsonl path")
    sn.add_argument("--seed", type=int, default=0,
                    help="generator seed (builtins only)")
    sn.add_argument("--out", dest="out_path", default=None,
                    help="output trace path "
                    "(default <name>.trace.jsonl)")
    sn.add_argument("--speed", type=float, default=1.0,
                    help="replay time warp (2 = twice as fast; "
                    "0 = as fast as possible)")
    sn.add_argument("--bind-timeout", type=float, default=120.0,
                    help="replay: seconds to wait for resident pods "
                    "to bind")
    sn.add_argument("--from-wal", dest="from_wal", default=None,
                    help="record: capture from a durable store's "
                    "wal.jsonl")
    sn.add_argument("--from-bundle", dest="from_bundle", default=None,
                    help="record: convert an audit repro bundle JSON")
    sn.add_argument("--chaos-seed", dest="chaos_seed", type=int,
                    default=None,
                    help="record --from-wal: arm this fault-schedule "
                    "seed in the captured manifest")

    ds = sub.add_parser("deschedule")
    ds.add_argument("action", choices=["run", "status"])
    ds.add_argument("--policy", default=None,
                    help="DeschedulerConfiguration YAML (profiles/knobs)")
    ds.add_argument("--dry-run", action="store_true",
                    help="plan and print, evict nothing")
    ds.add_argument("--max-evictions", type=int, default=None)
    ds.add_argument("-o", "--output", choices=["table", "json"],
                    default="table")
    return ap


def main(argv=None, out=None) -> int:
    out = out or sys.stdout
    args = build_parser().parse_args(argv)
    if args.cmd == "lint":  # no apiserver involved: dispatch before client
        from kubernetes_tpu.analysis.cli import main as lint_main
        lint_argv = list(args.lint_paths)
        if args.baseline:
            lint_argv += ["--baseline", args.baseline]
        if args.write_baseline:
            lint_argv.append("--write-baseline")
        if args.no_baseline:
            lint_argv.append("--no-baseline")
        if args.lint_json:
            lint_argv.append("--json")
        for r in args.rule or ():
            lint_argv += ["--rule", r]
        return lint_main(lint_argv, out=out)
    if args.cmd == "scenario" and args.action != "replay":
        # generate/record/describe are pure file operations: dispatch
        # before the client so they work with no apiserver running
        return cmd_scenario(None, args, out)
    client = HTTPClient(args.server, token=args.token,
                        user_agent="ktpu")
    try:
        if args.cmd == "get":
            return cmd_get(client, args, out)
        if args.cmd == "apply":
            return cmd_apply(client, args, out)
        if args.cmd == "delete":
            return cmd_delete(client, args, out)
        if args.cmd == "describe":
            return cmd_describe(client, args, out)
        if args.cmd == "certificate":
            from kubernetes_tpu.controllers.certificates import (approve_csr,
                                                                 deny_csr)
            fn = approve_csr if args.action == "approve" else deny_csr
            fn(client, args.name)
            verb = "approved" if args.action == "approve" else "denied"
            out.write(f"certificatesigningrequest/{args.name} {verb}\n")
            return 0
        if args.cmd == "scale":
            return cmd_scale(client, args, out)
        if args.cmd == "cordon":
            return _set_unschedulable(client, args.name, True, out)
        if args.cmd == "uncordon":
            return _set_unschedulable(client, args.name, False, out)
        if args.cmd == "drain":
            return cmd_drain(client, args, out)
        if args.cmd == "logs":
            return cmd_logs(client, args, out)
        if args.cmd == "exec":
            args.command = [c for c in args.command if c != "--"]
            return cmd_exec(client, args, out)
        if args.cmd == "port-forward":
            args.server = client.base
            return cmd_port_forward(client, args, out)
        if args.cmd == "top":
            return cmd_top(client, args, out)
        if args.cmd == "label":
            return cmd_label(client, args, out, field="labels")
        if args.cmd == "annotate":
            return cmd_label(client, args, out, field="annotations")
        if args.cmd == "api-resources":
            return cmd_api_resources(client, args, out)
        if args.cmd == "wait":
            return cmd_wait(client, args, out)
        if args.cmd == "attach":
            # attach to the main container's stream: the hollow runtime has
            # no live stdout stream, so attach surfaces the current logs
            # (the closest observable analog of the attached terminal)
            out.write(client.pod_logs(args.namespace, args.name,
                                      container=args.container or ""))
            return 0
        if args.cmd == "rollout":
            args.name = args.kind_name.split("/", 1)[-1]
            return cmd_rollout(client, args, out)
        if args.cmd == "status":
            return cmd_status(client, args, out)
        if args.cmd == "autoscale":
            return cmd_autoscale(client, args, out)
        if args.cmd == "audit":
            return cmd_audit(client, args, out)
        if args.cmd == "why":
            return cmd_why(client, args, out)
        if args.cmd == "trace":
            return cmd_trace(client, args, out)
        if args.cmd == "scenario":
            return cmd_scenario(client, args, out)
        if args.cmd == "deschedule":
            return cmd_deschedule(client, args, out)
    except ApiError as e:
        out.write(f"Error from server ({e.reason or e.code}): {e}\n")
        return 1
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
