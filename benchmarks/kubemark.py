"""Kubemark-scale e2e: hundreds of hollow kubelets + the connected
scheduler against the separate-process apiserver.

Reference: ``pkg/kubemark`` + sig-scalability's 5k-node control-plane
tests: real node-agent code over a mocked CRI exercising the WHOLE loop —
node registration and heartbeats through the API, the scheduler binding
through its informers, kubelets observing their bindings over the shared
watch and driving pods to Running with status writes the scheduler's cache
then confirms. Measures pods-to-Running throughput and heartbeat-fleet
health under that load.
"""

from __future__ import annotations

import multiprocessing as mp
import time


def run_kubemark(n_hollow: int = 500, n_pods: int = 1000,
                 heartbeat_period: float = 10.0, timeout: float = 240.0,
                 log=lambda *a: None) -> dict:
    from benchmarks.connected import _serve, _span_totals, _trace_window
    from kubernetes_tpu.client.clientset import HTTPClient
    from kubernetes_tpu.config.types import SchedulerConfiguration
    from kubernetes_tpu.kubelet.kubemark import HollowCluster
    from kubernetes_tpu.sched.runner import SchedulerRunner
    from kubernetes_tpu.testing.wrappers import make_pod
    from kubernetes_tpu.utils.tracing import TRACER

    ctx = mp.get_context("spawn")
    parent, child = ctx.Pipe()
    server = ctx.Process(target=_serve, args=(child,), daemon=True)
    server.start()
    port = parent.recv()
    url = f"http://127.0.0.1:{port}"
    cluster = runner = None
    try:
        # span the whole run (register -> bind -> Running) the way the
        # connected bench is spanned, so a BENCH file shows where the
        # seconds go: registration, scheduler sync, status writes (batched
        # flushes appear as kubemark/status_flush), heartbeats
        _trace_window()
        t0 = time.time()
        with TRACER.span("kubemark/register", nodes=n_hollow):
            cluster = HollowCluster(HTTPClient(url, timeout=60.0), n_hollow,
                                    heartbeat_period=heartbeat_period).start()
        t_reg = time.time() - t0
        log(f"  {n_hollow} hollow nodes registered in {t_reg:.1f}s")

        with TRACER.span("kubemark/scheduler_sync"):
            runner = SchedulerRunner(
                HTTPClient(url), SchedulerConfiguration(batch_size=256,
                                                        max_drain_batches=2))
            runner.start(wait_sync=60.0)

        client = HTTPClient(url, timeout=60.0)
        pods = [make_pod(f"km-{i}", "default")
                .req({"cpu": "100m", "memory": "64Mi"}).obj().to_dict()
                for i in range(n_pods)]
        t_start = time.time()
        with TRACER.span("kubemark/create_pods", pods=n_pods):
            client.pods("default").create_many(pods)
        deadline = t_start + timeout
        bound = running = 0
        milestones: dict = {}  # phase -> seconds since t_start
        while time.time() < deadline:
            listed = client.pods("default").list()
            bound = sum(1 for p in listed if p["spec"].get("nodeName"))
            running = sum(1 for p in listed
                          if (p.get("status") or {}).get("phase")
                          == "Running")
            if bound >= n_pods and "all_bound" not in milestones:
                milestones["all_bound"] = round(time.time() - t_start, 2)
            for frac, key in ((0.5, "half_running"), (1.0, "all_running")):
                if running >= n_pods * frac and key not in milestones:
                    milestones[key] = round(time.time() - t_start, 2)
            if running >= n_pods:
                break
            time.sleep(0.5)
        dt = time.time() - t_start
        # fleet health: Ready heartbeats landing under load
        ready = sum(
            1 for n in client.nodes().list()
            if any(c.get("type") == "Ready" and c.get("status") == "True"
                   for c in (n.get("status") or {}).get("conditions") or []))
        log(f"  {bound} bound, {running} running at +{dt:.1f}s; "
            f"{ready}/{n_hollow} nodes Ready")
        return {
            "case": "Kubemark",
            "workload": f"{n_pods}pods_{n_hollow}hollow",
            "hollow_nodes": n_hollow, "pods": n_pods,
            "register_s": round(t_reg, 1),
            "bound": bound, "running": running,
            "RunningThroughput": round(running / dt, 1) if dt > 0 else 0.0,
            "measure_s": round(dt, 2),
            "nodes_ready": ready,
            "milestones": milestones,
            "span_ms": _span_totals(),
        }
    finally:
        try:
            if runner is not None:
                runner.stop()
            if cluster is not None:
                cluster.stop()
        except Exception:
            pass
        try:
            parent.send("stop")
        except Exception:
            pass
        server.join(timeout=5.0)
        if server.is_alive():
            server.terminate()


if __name__ == "__main__":
    import json
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    res = run_kubemark(
        n_hollow=int(os.environ.get("BENCH_KUBEMARK_NODES", "500")),
        n_pods=int(os.environ.get("BENCH_KUBEMARK_PODS", "1000")),
        log=lambda *a: print(*a, file=sys.stderr))
    print(json.dumps(res))
