"""ScenarioReplay bench case: the cluster time machine against the real
connected stack.

Resolves a trace (``builtin:<name>`` from the generator catalog, or a
``.trace.jsonl`` path — committed fixture, WAL capture, or audit-bundle
conversion), seeds its node fleet into a separate-process apiserver,
arms the manifest's chaos schedule if it carries one, and replays the
events through the time-warped driver while the fail-fast invariant
auditor sweeps the whole window.

Hard gates (reported as ``slo_failures``; bench.py exits non-zero):

* every trace-resident pod bound (lost pods fail, like ChaosChurn)
* per-phase p99 attempt latency PRESENT for every phase that left
  resident pods — a missing number fails exactly like a regressed one
* determinism: two independent resolutions of the same spec+seed plan
  the same dispatch order, and the live run dispatched exactly that plan
* the manifest's own sloGates (check_slo_gates vocabulary)
* 0 confirmed invariant violations (via the shared audit roll-up)
"""

from __future__ import annotations

import multiprocessing as mp
import time


def _resolve(spec: str, seed: int = 0):
    """``builtin:<name>`` -> generator catalog; anything else is a path."""
    from kubernetes_tpu.scenario import Trace, builtin_trace
    if spec.startswith("builtin:"):
        return builtin_trace(spec[len("builtin:"):], seed=seed)
    return Trace.load(spec)


def run_scenario_replay(spec: str = "builtin:smoke", speed: float = 4.0,
                        seed: int = 0, timeout: float = 180.0,
                        batch_size: int = 64,
                        log=lambda *a: None) -> dict:
    from benchmarks.connected import (_audit_close, _bench_auditor,
                                      _serve, check_slo_gates)
    from kubernetes_tpu.api.types import Pod
    from kubernetes_tpu.client.clientset import HTTPClient
    from kubernetes_tpu.config.types import SchedulerConfiguration
    from kubernetes_tpu.metrics.registry import ATTEMPT_DURATION
    from kubernetes_tpu.sched.runner import SchedulerRunner
    from kubernetes_tpu.scenario import ScenarioDriver

    trace = _resolve(spec, seed=seed)
    # determinism gate, half 1: a SECOND independent resolution of the
    # same spec+seed must plan the identical dispatch order (generators
    # are pure; a file is just bytes)
    plan = ScenarioDriver(None, trace, publish=False).plan()
    plan2 = ScenarioDriver(None, _resolve(spec, seed=seed),
                           publish=False).plan()
    resident = trace.resident_pods()
    log(f"  trace {trace.manifest.name!r}: {len(trace)} events, "
        f"{len(resident)} resident pods, "
        f"{trace.duration_s():.1f}s at speed {speed}")

    ctx = mp.get_context("spawn")  # same rule as run_connected
    parent, child = ctx.Pipe()
    server = ctx.Process(target=_serve, args=(child,), daemon=True)
    server.start()
    port = parent.recv()
    url = f"http://127.0.0.1:{port}"
    schedule = device_chaos = None
    try:
        seed_client = HTTPClient(url, timeout=120.0)
        fleet = trace.fleet_nodes()
        if fleet:
            seed_client.nodes().create_many(fleet)
            log(f"  seeded {len(fleet)} fleet nodes")

        cfg_kw = dict(batch_size=batch_size, max_drain_batches=2)
        sched_client = HTTPClient(url)
        chaos_cfg = trace.manifest.chaos
        if chaos_cfg:
            # the recorded incident's fault schedule rides the manifest:
            # the SCHEDULER's transport is chaos-wrapped, the harness's
            # own clients stay clean (the bench owns ground truth)
            from kubernetes_tpu.chaos import ChaosClient, FaultSchedule
            schedule = FaultSchedule.generate(
                int(chaos_cfg.get("seed", 0)),
                profile=chaos_cfg.get("profile", "churn"))
            log(f"  chaos schedule armed (seed {schedule.seed})")
            sched_client = ChaosClient(sched_client, schedule)
            cfg_kw["breaker_cooldown_s"] = 5.0
            cfg_kw["parity_sample_every"] = 4
        runner = SchedulerRunner(sched_client,
                                 SchedulerConfiguration(**cfg_kw))
        runner.auditor = _bench_auditor(runner, HTTPClient(url))
        runner.start(start_loop=False)

        # warm the fused drain at the replay's shapes so the window is
        # steady state (a trace pod that eats a compile would post a
        # multi-second "attempt latency" that is really XLA's)
        warm_pods = []
        for ev in resident.values():
            try:
                warm_pods.append(Pod.from_dict(trace.materialize(ev)))
            except Exception:
                break  # recorded objs may predate the model's schema
        jit_warmed = False
        if len(warm_pods) == len(resident):
            t0 = time.time()
            jit_warmed = runner.scheduler.warm_drain(
                warm_pods, slot_headroom=len(warm_pods)
                + batch_size * runner.cfg.max_drain_batches)
            log(f"  jit warmup {time.time()-t0:.1f}s "
                f"(ctx armed: {jit_warmed})")

        if schedule is not None:
            from kubernetes_tpu.chaos import (DeviceChaos, ThreadChaos,
                                              hooks)
            device_chaos = DeviceChaos(schedule).install()
            hooks.install(ThreadChaos(schedule))

        runner.start_loop()
        # process-global registry: earlier bench phases must not pollute
        # this window's scheduler-side p99
        ATTEMPT_DURATION.reset()

        driver = ScenarioDriver(HTTPClient(url), trace, speed=speed,
                                bind_timeout_s=timeout, log=log)
        replay = driver.run()
        log(f"  replay: {replay['bound']}/{replay['resident']} bound "
            f"in {replay['wall_s']}s "
            f"(skew max {replay['skew']['max_s']}s)")

        p99 = ATTEMPT_DURATION.percentile(0.99, {"result": "scheduled"})
        p50 = ATTEMPT_DURATION.percentile(0.50, {"result": "scheduled"})

        if schedule is not None:
            from kubernetes_tpu.chaos import hooks
            hooks.uninstall()
            if device_chaos is not None:
                device_chaos.uninstall()
                device_chaos = None
        audit_block = _audit_close(runner)
        runner.stop()

        deterministic = (plan == plan2
                         and replay["dispatch_order"] == plan)
        wall = replay["wall_s"] or 1e-9
        out = {
            "case": "ScenarioReplay",
            "spec": spec,
            "trace": replay["trace"],
            "seed": replay["seed"],
            "speed": speed,
            "events_total": replay["events_total"],
            "dispatched": replay["dispatched"],
            "dispatch_error_count": replay["error_count"],
            "dispatch_errors": replay["errors"][:10],
            "resident": replay["resident"],
            "bound": replay["bound"],
            "lost": replay["resident"] - replay["bound"],
            "completed": replay["completed"],
            "dispatch_s": replay["dispatch_s"],
            "wall_s": replay["wall_s"],
            "SchedulingThroughput": round(replay["bound"] / wall, 1),
            "skew": replay["skew"],
            "phases": replay["phases"],
            "p99_attempt_latency_s": p99,
            "p50_attempt_latency_s": p50,
            "deterministic": deterministic,
            "jit_warmed": jit_warmed,
        }
        if schedule is not None:
            out["chaos"] = {"seed": schedule.seed,
                            "recovery": schedule.report()}
        out.update(audit_block)

        failures: list[str] = []
        if out["lost"]:
            failures.append(f"{out['lost']} of {out['resident']} "
                            "trace-resident pods never bound")
        for ph, st in sorted(replay["phases"].items()):
            if st["pods"] and not isinstance(
                    st.get("p99_attempt_latency_s"), (int, float)):
                failures.append(
                    f"phase {ph!r}: p99 attempt latency missing "
                    f"({st['pods']} pods) — gate cannot pass silently")
        if not deterministic:
            failures.append("replay is not deterministic: dispatch "
                            "order diverged from the plan (or two "
                            "resolutions of the spec disagree)")
        failures.extend(check_slo_gates(out, trace.manifest.slo_gates))
        out["slo_failures"] = failures
        return out
    finally:
        if schedule is not None:  # crash path: never leak installed chaos
            from kubernetes_tpu.chaos import hooks as _hooks
            _hooks.uninstall()
            if device_chaos is not None:
                device_chaos.uninstall()
        try:
            parent.send("stop")
        except Exception:
            pass
        server.join(timeout=5.0)
        if server.is_alive():
            server.terminate()


if __name__ == "__main__":
    import json
    import os
    import sys
    sys.path.insert(0, os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    spec = os.environ.get("BENCH_SCENARIO", "builtin:smoke")
    res = run_scenario_replay(
        spec="builtin:smoke" if spec in ("", "1") else spec,
        speed=float(os.environ.get("BENCH_SCENARIO_SPEED", "4")),
        seed=int(os.environ.get("BENCH_SCENARIO_SEED", "0")),
        log=lambda *a: print(*a, file=sys.stderr))
    print(json.dumps(res))
