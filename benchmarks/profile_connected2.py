"""Busy-thread sampling profile of the connected run's measured window."""
import collections
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

IDLE = {"wait", "select", "poll", "accept", "_wait_for_tstate_lock",
        "get", "readline", "readinto", "recv", "recv_into"}
samples = collections.Counter()
stop = threading.Event()
started = threading.Event()


def sampler():
    started.wait()
    while not stop.is_set():
        for tid, frame in sys._current_frames().items():
            if tid == threading.get_ident():
                continue
            stack = []
            f = frame
            while f is not None and len(stack) < 50:
                stack.append(f)
                f = f.f_back
            top = stack[0].f_code
            if top.co_name in IDLE:
                # attribute to the nearest repo frame below, if any is NOT
                # an idle wrapper (i.e. the thread is blocked, skip it)
                continue
            # attribute to top frame plus nearest repo frame
            repo = next((g for g in stack
                         if "/repo/" in g.f_code.co_filename), None)
            key = f"{os.path.basename(top.co_filename)}:{top.co_name}"
            if repo is not None and repo.f_code is not top:
                key += f" <{os.path.basename(repo.f_code.co_filename)}:{repo.f_code.co_name}>"
            samples[key] += 1
        time.sleep(0.002)


t = threading.Thread(target=sampler, daemon=True)
t.start()


def log(*a):
    print(*a, file=sys.stderr)
    if "warmup" in str(a[0]):
        started.set()


from benchmarks.connected import run_connected
res = run_connected(n_pods=int(os.environ.get("PODS", "10000")),
                    n_nodes=int(os.environ.get("NODES", "5000")),
                    log=log)
stop.set()
print(res)
total = sum(samples.values())
print(f"--- busy samples: {total} ---")
for k, v in samples.most_common(35):
    print(f"{v:6d} {100*v/max(total,1):5.1f}% {k}")
