"""Preemption benchmark: victim search throughput at fleet scale.

The reference's preemption hot path is ``DryRunPreemption``
(``pkg/scheduler/framework/preemption/preemption.go``): per failed pod,
simulate victim eviction on every candidate node (16 goroutines). Here the
whole WAVE of preemptors runs as one [Q,N,V+1] sequential-commit scan
(ops/preemption.py ``_wave_scan``) with each proposal exactly verified
host-side against a shared oracle — this measures end-to-end
``preempt_wave`` throughput (preemptors/second) on a saturated cluster, vs
the pure-host serial scan on a sample for the speedup ratio.

Scenario: every node is full of low-priority pods; a wave of high-priority
pods arrives, each needing victims. Each preemptor's chosen victims are
evicted from the bound set before the next (sequential cluster mutation,
like the real failure path).
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_saturated(n_nodes: int, pods_per_node: int = 2):
    from kubernetes_tpu.testing.wrappers import make_node, make_pod
    nodes = [make_node(f"n{i}").capacity(
        {"cpu": "8", "memory": "32Gi", "pods": "32"}).obj()
        for i in range(n_nodes)]
    bound = []
    for i in range(n_nodes):
        for j in range(pods_per_node):
            bound.append(
                make_pod(f"low-{i}-{j}")
                .req({"cpu": "4", "memory": "4Gi"})
                .priority(1 + (i + j) % 5).node(f"n{i}").obj())
    return nodes, bound


def run_preemption(n_nodes: int = 5000, n_preemptors: int = 256,
                   host_sample: int = 8, log=lambda *a: None) -> dict:
    from kubernetes_tpu.sched.preemption import find_candidate, preempt_wave
    from kubernetes_tpu.testing.wrappers import make_pod

    nodes, bound = build_saturated(n_nodes)
    preemptors = [make_pod(f"hi-{k}").req({"cpu": "6", "memory": "8Gi"})
                  .priority(100).obj() for k in range(n_preemptors)]
    log(f"  {n_nodes} nodes saturated with {len(bound)} low-priority pods")

    # warmup: compile the wave scan + static-mask filters at this shape
    # (the wave mutates nothing — inputs are re-encoded per call)
    preempt_wave(nodes, bound, preemptors)

    t0 = time.time()
    results = preempt_wave(nodes, bound, preemptors)
    resolved = sum(r is not None for r in results)
    dt = time.time() - t0
    tensor_rate = resolved / dt if dt > 0 else 0.0

    # host-serial comparison on a small sample (the full sweep would take
    # minutes at fleet scale — that is the point)
    t0 = time.time()
    for pod in preemptors[:host_sample]:
        find_candidate(nodes, bound, pod)
    host_dt = time.time() - t0
    host_rate = host_sample / host_dt if host_dt > 0 else 0.0

    return {
        "case": "Preemption", "workload": f"{n_preemptors}x{n_nodes}",
        "PreemptionThroughput": round(tensor_rate, 1),
        "resolved": resolved, "preemptors": n_preemptors, "nodes": n_nodes,
        "measure_s": round(dt, 2),
        "host_serial_per_sec": round(host_rate, 2),
        "speedup_vs_host": (round(tensor_rate / host_rate, 1)
                            if host_rate else None),
    }


if __name__ == "__main__":
    import json
    res = run_preemption(
        n_nodes=int(os.environ.get("BENCH_PREEMPT_NODES", "5000")),
        n_preemptors=int(os.environ.get("BENCH_PREEMPT_PODS", "256")),
        log=lambda *a: print(*a, file=sys.stderr))
    print(json.dumps(res))
