"""SliceCarve: sustained contiguous-slice churn through the carve path.

One apiserver + one connected scheduler over a labeled ICI torus
(``kubernetes-tpu.io/topology-{x,y,z}`` node labels); a few cells are
pinned near-full so every carve must route around fragmentation. The
window submits slice gangs (``kubernetes-tpu.io/slice-shape``) back to
back: each gang must land on one CONTIGUOUS torus box, bind fully, and
clear before the next.

Hard gates (missing number = failure, PR-8 discipline):
  - every carved gang occupies a contiguous box of the requested shape
    (topology/slicing.is_contiguous_slice over the bound API state),
  - 0 invariant violations (fail-fast auditor live, slice_contiguity
    included),
  - ZERO XLA compiles in the steady window — the carve's (dims, rots)
    static args are fixed per installed topology, so steady-state carves
    ride one warm program,
  - the ParitySentinel's carve site (armed at every=1) confirms every
    device carve against the numpy oracle carver: 0 divergences.
"""

from __future__ import annotations

import time


def run_slice_carve(grid: str = "4x4x2", shape: str = "2x2x2",
                    node_cpu: str = "8", member_cpu: str = "2",
                    n_fragment: int = 4, window_s: float = 10.0,
                    carve_timeout_s: float = 30.0,
                    log=lambda *a: None) -> dict:
    from benchmarks.connected import _audit_close, _bench_auditor
    from benchmarks.fleetchurn import _CompileCounter, _p99
    from kubernetes_tpu.client.clientset import HTTPClient
    from kubernetes_tpu.config.types import SchedulerConfiguration
    from kubernetes_tpu.sched.runner import SchedulerRunner
    from kubernetes_tpu.store.apiserver import APIServer
    from kubernetes_tpu.testing.wrappers import make_node, make_pod
    from kubernetes_tpu.topology.slicing import (GANG_LABEL,
                                                 SLICE_SHAPE_LABEL,
                                                 coords_of_labels,
                                                 is_contiguous_slice,
                                                 parse_shape,
                                                 topology_labels)

    dims = parse_shape(grid)
    shp = parse_shape(shape)
    want = shp[0] * shp[1] * shp[2]
    server = None
    runner = None
    failures: list[str] = []
    result: dict = {"case": "SliceCarve",
                    "workload": f"{grid}grid_{shape}slices_"
                                f"frag{n_fragment}",
                    "grid": grid, "shape": shape, "window_s": window_s}
    try:
        server = APIServer().start()
        client = HTTPClient(server.url, timeout=60.0)
        cells = [(x, y, z) for x in range(dims[0]) for y in range(dims[1])
                 for z in range(dims[2])]
        for x, y, z in cells:
            nb = make_node(f"tn-{x}-{y}-{z}").capacity(
                {"cpu": node_cpu, "memory": "16Gi", "pods": "32"})
            for k, v in topology_labels(x, y, z).items():
                nb = nb.label(k, v)
            client.nodes().create(nb.obj().to_dict())
        # fragment: pin near-full pods on spread-out cells so those cells
        # can never host a member — every carve must route around them
        frag_cells = cells[:: max(1, len(cells) // max(1, n_fragment))][
            :n_fragment]
        frag = int(node_cpu) * 1000 - 500  # 500m headroom: under member_cpu
        for i, (x, y, z) in enumerate(frag_cells):
            client.pods("default").create(
                make_pod(f"frag-{i}").req({"cpu": f"{frag}m"})
                .node(f"tn-{x}-{y}-{z}").obj().to_dict())
        result["nodes"] = len(cells)
        result["fragmented_cells"] = len(frag_cells)

        runner = SchedulerRunner(client, SchedulerConfiguration(
            batch_size=max(8, want), backoff_initial_s=0.05,
            backoff_max_s=0.2))
        runner.auditor = _bench_auditor(runner, HTTPClient(server.url))
        runner.start(wait_sync=30.0)
        runner.scheduler.sentinel.every = 1  # judge EVERY carve
        node_coords = {f"tn-{x}-{y}-{z}": (x, y, z) for x, y, z in cells}

        def run_gang(gid: str) -> tuple:
            """Submit one slice gang, wait for full bind -> (bind seconds
            or None, placements). Deletes the gang's pods afterwards."""
            names = [f"{gid}-{m}" for m in range(want)]
            t0 = time.time()
            client.pods("default").create_many(
                [make_pod(n).req({"cpu": member_cpu})
                 .labels({GANG_LABEL: gid, SLICE_SHAPE_LABEL: shape})
                 .obj().to_dict() for n in names])
            placed: dict = {}
            deadline = t0 + carve_timeout_s
            while time.time() < deadline and len(placed) < want:
                for p in client.pods("default").list():
                    nm = p["metadata"]["name"]
                    if nm in names and (p.get("spec") or {}).get("nodeName"):
                        placed[nm] = p["spec"]["nodeName"]
                time.sleep(0.05)
            took = (time.time() - t0) if len(placed) == want else None
            for n in names:
                try:
                    client.pods("default").delete(n)
                except Exception:
                    pass
            return took, placed

        # ---- warm leg: compile the carve + group-path programs at the
        # window's exact static args (dims, rots, buckets) ----------------
        compiles = _CompileCounter()
        took, placed = run_gang("warm")
        if took is None:
            failures.append(f"warm gang never fully bound "
                            f"({len(placed)}/{want})")
        result["warmup_quiet_s"] = round(
            compiles.wait_quiet(quiet_s=3.0, timeout_s=45.0), 1)

        # ---- steady window: back-to-back carves, zero compiles -----------
        compiles.arm()
        t_win = time.time()
        carves = 0
        contiguous_ok = 0
        lat: list[float] = []
        while time.time() - t_win < window_s:
            gid = f"g{carves}"
            took, placed = run_gang(gid)
            if took is None:
                failures.append(f"gang {gid}: only {len(placed)}/{want} "
                                f"members bound within {carve_timeout_s}s")
                break
            lat.append(took)
            carves += 1
            coords = [node_coords.get(nn) for nn in placed.values()]
            if (all(c is not None for c in coords)
                    and is_contiguous_slice(coords, shp, dims)):
                contiguous_ok += 1
            else:
                failures.append(f"gang {gid}: members NOT on a contiguous "
                                f"{shape} box: {sorted(placed.items())}")
        xla_compiles = compiles.disarm()
        result["carves"] = carves
        result["contiguous_ok"] = contiguous_ok
        result["carves_per_s"] = round(carves / window_s, 2)
        result["p99_carve_bind_s"] = _p99(lat)
        result["ctx_window"] = {"xla_compiles": xla_compiles}
        if carves <= 0:
            failures.append("no carve completed in the window — the gate "
                            "cannot pass silently")
        if xla_compiles != 0:
            failures.append(f"one-warm-program violated: {xla_compiles} "
                            "XLA compile(s) during the steady window")

        status = runner.scheduler.topology_status()
        result["topology"] = status
        if status is None:
            failures.append("topology status missing: the scheduler saw "
                            "no coordinates")
        result.update(_audit_close(runner))
        if result.get("invariant_violations") is None:
            failures.append("invariant_violations missing")
        parity = result.get("parity") or {}
        if parity.get("samples", {}).get("carve", 0) < carves:
            failures.append(
                f"sentinel carve site sampled "
                f"{parity.get('samples', {}).get('carve', 0)} of {carves} "
                "carves at every=1")
        if parity.get("divergences"):
            failures.append(f"{parity['divergences']} carve parity "
                            "divergence(s) — device/oracle split")
    finally:
        try:
            if runner is not None:
                runner.stop()
        except Exception:
            pass
        try:
            if server is not None:
                server.stop()
        except Exception:
            pass
    result["slo_failures"] = failures
    return result


if __name__ == "__main__":
    import json
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    res = run_slice_carve(
        grid=os.environ.get("BENCH_SLICE_GRID", "4x4x2"),
        shape=os.environ.get("BENCH_SLICE_SHAPE", "2x2x2"),
        window_s=float(os.environ.get("BENCH_SLICE_WINDOW_S", "10")),
        n_fragment=int(os.environ.get("BENCH_SLICE_FRAG", "4")),
        log=lambda *a: print(*a, file=sys.stderr))
    print(json.dumps(res))
    if res.get("slo_failures") or res.get("invariant_violations"):
        sys.exit(1)
