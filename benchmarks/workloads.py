"""Workload generators for the five BASELINE.json benchmark configs.

Mirrors the shape of scheduler_perf's YAML-driven workloads
(test/integration/scheduler_perf/config/performance-config.yaml):
createNodes -> createPods with templated specs. Deterministic via seed:
every generator derives ALL randomness from its own ``random.Random(seed)``
(never the module-level RNG), so the same (params, seed) yields the same
objects — pinned by the same-seed-twice test, and relied on by the
scenario engine, which reuses these shapes as trace template pools.
"""

from __future__ import annotations

import random

from kubernetes_tpu.testing.wrappers import make_node, make_pod

ZONES = [f"zone-{i}" for i in range(10)]


def nodes_basic(n: int, cpu: str = "32", mem: str = "128Gi", pods: str = "110"):
    out = []
    for i in range(n):
        out.append(make_node(f"node-{i}")
                   .capacity({"cpu": cpu, "memory": mem, "pods": pods})
                   .label("topology.kubernetes.io/zone", ZONES[i % len(ZONES)])
                   .obj())
    return out


def scheduling_basic(pods: int = 100, nodes: int = 100, seed: int = 0):
    """SchedulingBasic: uniform pods onto uniform nodes."""
    rng = random.Random(seed)
    ns = nodes_basic(nodes)
    ps = [make_pod(f"pod-{i}")
          .req({"cpu": rng.choice(["100m", "250m", "500m"]),
                "memory": rng.choice(["128Mi", "256Mi", "512Mi"])}).obj()
          for i in range(pods)]
    return ns, ps


def noderesources_fit(pods: int = 5000, nodes: int = 1000, seed: int = 0):
    """Config 2: cpu+mem requests onto heterogeneous nodes (pure Fit/score)."""
    rng = random.Random(seed)
    ns = []
    for i in range(nodes):
        cpu = rng.choice(["8", "16", "32", "64"])
        mem = rng.choice(["32Gi", "64Gi", "128Gi"])
        ns.append(make_node(f"node-{i}").capacity(
            {"cpu": cpu, "memory": mem, "pods": "110"}).obj())
    ps = [make_pod(f"pod-{i}")
          .req({"cpu": rng.choice(["250m", "500m", "1", "2"]),
                "memory": rng.choice(["256Mi", "1Gi", "4Gi"])}).obj()
          for i in range(pods)]
    return ns, ps


def pod_anti_affinity(pods: int = 1000, nodes: int = 500, seed: int = 0):
    """SchedulingPodAntiAffinity: required hostname anti-affinity per group —
    the textbook serial-scheduler killer."""
    rng = random.Random(seed)
    ns = nodes_basic(nodes)
    groups = max(pods // (nodes // 2), 2)
    ps = []
    for i in range(pods):
        g = f"g{i % groups}"
        ps.append(make_pod(f"pod-{i}").label("group", g)
                  .req({"cpu": "100m", "memory": "128Mi"})
                  .pod_anti_affinity("kubernetes.io/hostname", {"group": g}).obj())
    return ns, ps


def preferred_topology_spreading(pods: int = 5000, nodes: int = 5000, seed: int = 0):
    """PreferredTopologySpreading: soft zone spread + hard hostname spread."""
    rng = random.Random(seed)
    ns = nodes_basic(nodes)
    ps = []
    for i in range(pods):
        ps.append(make_pod(f"pod-{i}").label("app", f"svc-{i % 50}")
                  .req({"cpu": "100m", "memory": "128Mi"})
                  .spread(1, "topology.kubernetes.io/zone", "ScheduleAnyway",
                          {"app": f"svc-{i % 50}"}).obj())
    return ns, ps


def mixed_heterogeneous(pods: int = 10000, nodes: int = 5000, seed: int = 0):
    """Config 5: 10k heterogeneous pods (affinity+spread+taints) on 5k nodes."""
    rng = random.Random(seed)
    ns = []
    for i in range(nodes):
        w = (make_node(f"node-{i}")
             .capacity({"cpu": rng.choice(["16", "32", "64"]),
                        "memory": rng.choice(["64Gi", "128Gi"]), "pods": "110"})
             .label("topology.kubernetes.io/zone", ZONES[i % len(ZONES)])
             .label("disk", rng.choice(["ssd", "hdd"])))
        if i % 20 == 0:
            w.taint("dedicated", "infra", "NoSchedule")
        ns.append(w.obj())
    ps = []
    for i in range(pods):
        w = (make_pod(f"pod-{i}").label("app", f"svc-{i % 100}")
             .req({"cpu": rng.choice(["100m", "250m", "500m", "1"]),
                   "memory": rng.choice(["128Mi", "512Mi", "1Gi"])}))
        r = rng.random()
        if r < 0.2:
            w.spread(2, "topology.kubernetes.io/zone", "ScheduleAnyway",
                     {"app": f"svc-{i % 100}"})
        elif r < 0.3:
            w.node_selector({"disk": "ssd"})
        elif r < 0.35:
            w.toleration(key="dedicated", operator="Equal", value="infra",
                         effect="NoSchedule")
        elif r < 0.4:
            w.preferred_pod_affinity(50, "topology.kubernetes.io/zone",
                                     {"app": f"svc-{i % 100}"})
        ps.append(w.obj())
    return ns, ps


def huge_cluster(pods: int = 4096, nodes: int = 16384, seed: int = 0):
    """Beyond-threshold scale: crosses ops/topology.py's
    ``_FACTORED_THRESHOLD`` (8192 nodes) so domain counting runs the
    factored O(N+V) formulation instead of one-hot matmuls — the 50k-node
    scaling design point. Hard AND soft spread constraints so both the
    filter and scoring factored paths execute."""
    rng = random.Random(seed)
    ns = []
    for i in range(nodes):
        ns.append(
            make_node(f"hn{i}")
            .capacity({"cpu": "16", "memory": "64Gi", "pods": "110"})
            .label("topology.kubernetes.io/zone", f"zone-{i % 64}")
            .obj())
    ps = []
    for i in range(pods):
        w = (make_pod(f"hp{i}").req({"cpu": "500m", "memory": "1Gi"})
             .label("app", f"s{i % 32}"))
        if rng.random() < 0.5:
            w.spread(1, "topology.kubernetes.io/zone", "DoNotSchedule",
                     {"app": f"s{i % 32}"})
        else:
            w.spread(2, "topology.kubernetes.io/zone", "ScheduleAnyway",
                     {"app": f"s{i % 32}"})
        ps.append(w.obj())
    return ns, ps


WORKLOADS = {
    "SchedulingBasic": scheduling_basic,
    "NodeResourcesFit": noderesources_fit,
    "SchedulingPodAntiAffinity": pod_anti_affinity,
    "PreferredTopologySpreading": preferred_topology_spreading,
    "MixedHeterogeneous": mixed_heterogeneous,
    "HugeCluster": huge_cluster,
}
