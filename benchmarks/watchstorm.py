"""WatchStorm: >=10k watchers against a 3-node front door — follower
replicas absorb the fan-out, the leader barely notices.

The serving-plane claim this bench gates: list/watch load scales OUT
across read replicas instead of UP on the leader. A 3-node raft group
(one subprocess per node, ``chaos/replica.py``) serves the front door;
~10k storm watchers attach in two cohorts:

  phase A (baseline)  ~300 watchers on the LEADER only. Pod churn runs;
                      the leader's fan-out span (ns per event pushed
                      into watcher queues) is measured.
  phase B (storm)     the remaining ~10k watchers attach on the two
                      REPLICAS (replica-served share >= 2/3). The same
                      churn runs again; the leader's span is re-measured.

Storm watchers are in-process ``store.watch()`` queues inside each
replica subprocess (10k real HTTP streams would measure the bench
client, not the plane — the per-watcher queue put IS the fan-out cost);
sentinel informers ride REAL HTTP watch streams through the spread
client for end-to-end coverage.

Hard gates (missing number = failure, the PR-8 SLO discipline):
  - leader fan-out span growth phaseB/phaseA <= ``span_growth_max``
    (default 1.2x) with replica-served watcher share >= 2/3
  - gap-free streams: every watcher in a cohort reports the IDENTICAL
    event signature (count / rv-sum / rv-xor / last-rv) — one missed or
    reordered event anywhere splits the histogram
  - 0 slow-consumer drops, 0 severed streams across the whole storm
  - replica staleness bound honored: max sampled replay lag <= budget,
    and no replica /readyz flap while healthy
  - replica SIGKILL mid-churn heals: spread-client informer converges
    to the leader's exact pod set (zero loss), the reborn replica
    snapshot-resyncs to /readyz 200 within ``heal_slo_s``
  - 0 invariant violations (gap/loss/drop counts, summed)

Env knobs (bench.py): BENCH_WATCHSTORM=0 skips; BENCH_WATCHSTORM_WATCHERS
(default 10500), BENCH_WATCHSTORM_PODS (churn size per phase, default
600; clamped so a stalled cohort cannot overflow its queue budget),
BENCH_WATCHSTORM_SPAN_GROWTH (default 1.2), BENCH_WATCHSTORM_HEAL_SLO
(default 90s)."""

from __future__ import annotations

import threading
import time
import urllib.request


def _free_ports(n: int, host: str = "127.0.0.1") -> list:
    import socket
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind((host, 0))
        ports.append(s.getsockname()[1])
        socks.append(s)
    for s in socks:
        s.close()
    return ports


def _find_leader(procs, timeout: float = 60.0):
    """-> (leader proc, [follower procs]); raises if no single leader."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        roles = {p.node_id: p.call(("status",)) for p in procs}
        leaders = [p for p in procs
                   if roles[p.node_id].get("role") == "leader"]
        if len(leaders) == 1:
            return leaders[0], [p for p in procs if p is not leaders[0]]
        time.sleep(0.2)
    raise TimeoutError(f"no single leader: {roles}")


def _churn(client, prefix: str, n: int) -> int:
    """Create n pods (bulk chunks) then delete them all — 2n watch events
    through every live pod watcher. -> committed event count.

    The client's transport-retry contract: a retried NAMED write that
    already committed surfaces as 409 — so a 409 here means "done", not
    "broken". Names are unique per phase, so settling each item
    individually after a batch 409 cannot double-create (the store
    rejects duplicates before journaling)."""
    from kubernetes_tpu.client.clientset import ApiError
    from kubernetes_tpu.testing.wrappers import make_pod
    pods = client.pods("default")
    names = [f"{prefix}-{i}" for i in range(n)]
    for lo in range(0, n, 100):
        chunk = names[lo:lo + 100]
        try:
            pods.create_many([make_pod(nm).obj().to_dict()
                              for nm in chunk])
        except ApiError as e:
            if e.code != 409:
                raise
            for nm in chunk:  # the batch raced its own retry: settle
                try:
                    pods.create(make_pod(nm).obj().to_dict())
                except ApiError as e2:
                    if e2.code != 409:
                        raise
    for nm in names:
        try:
            pods.delete(nm)
        except ApiError as e:
            if e.code != 404:  # a retried delete that already landed
                raise
    return 2 * n


class _LagSampler:
    """Samples every replica's /frontdoor/status over HTTP while churn
    runs: max replay lag observed + readyz flaps on healthy replicas.
    HTTP (not the control pipe) so it can run beside the orchestrator."""

    def __init__(self, urls, period_s: float = 0.5):
        self.urls = list(urls)
        self.period_s = period_s
        self.max_lag_ms = 0.0
        self.samples = 0
        self.readyz_failures = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="watchstorm-lag-sampler")

    def _loop(self):
        import json as _json
        while not self._stop.is_set():
            for url in self.urls:
                try:
                    with urllib.request.urlopen(url + "/frontdoor/status",
                                                timeout=2.0) as resp:
                        st = _json.loads(resp.read())
                    lag = st.get("replayLagMs")
                    if lag is not None:
                        self.max_lag_ms = max(self.max_lag_ms, float(lag))
                        self.samples += 1
                    with urllib.request.urlopen(url + "/readyz",
                                                timeout=2.0):
                        pass
                except Exception:
                    self.readyz_failures += 1
            self._stop.wait(self.period_s)

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=10.0)


def run_watch_storm(n_watchers: int = 10500, churn_pods: int = 600,
                    leader_watchers: int = 300,
                    span_growth_max: float = 1.2,
                    min_replica_share: float = 2.0 / 3.0,
                    lag_budget_ms: float = 2000.0,
                    heal_slo_s: float = 90.0, log=print) -> dict:
    from kubernetes_tpu.chaos.replica import ReplicaProcess
    from kubernetes_tpu.client.clientset import HTTPClient
    from kubernetes_tpu.client.informer import SharedInformer
    from kubernetes_tpu.store.frontdoor import FrontDoorPublisher
    from kubernetes_tpu.store.store import WATCH_QUEUE_MAX

    # a stalled cohort-A queue holds BOTH phases' events (4*churn_pods);
    # overflowing the per-watcher budget by construction would gate on
    # the bench's own sizing, not the plane
    cap = WATCH_QUEUE_MAX // 4 - 64
    if churn_pods > cap:
        log(f"[watchstorm] churn {churn_pods} pods exceeds the per-watcher "
            f"queue budget for two phases; clamping to {cap}")
        churn_pods = cap
    # the baseline cohort must stay a sliver of the storm, whatever size
    # the env knobs pick — otherwise the replica-share gate measures the
    # bench's sizing, not the plane's routing
    leader_watchers = min(leader_watchers, max(1, n_watchers // 10))

    host = "127.0.0.1"
    raft_ports = _free_ports(3, host)
    api_ports = _free_ports(3, host)
    node_ids = [f"n{i}" for i in range(3)]
    raft_urls = {nid: f"http://{host}:{raft_ports[i]}"
                 for i, nid in enumerate(node_ids)}
    api_urls = {nid: f"http://{host}:{api_ports[i]}"
                for i, nid in enumerate(node_ids)}
    result: dict = {"case": "WatchStorm"}
    failures: list = []
    procs: list = []
    sampler = None
    informer = None
    try:
        for i, nid in enumerate(node_ids):
            peers = {p: raft_urls[p] for p in node_ids if p != nid}
            procs.append(ReplicaProcess(nid, raft_ports[i], api_ports[i],
                                        peers, api_urls,
                                        host=host).start())
        leader, replicas = _find_leader(procs)
        log(f"[watchstorm] leader={leader.node_id} "
            f"replicas={[r.node_id for r in replicas]}")
        for p in procs:
            p.wait_ready()
        endpoints = [p.url for p in procs]
        spread = HTTPClient(endpoints)
        leader_c = HTTPClient(leader.url)
        # the leader seeds system namespaces; followers skipped theirs
        for ns in ("default", "kube-system"):
            try:
                spread.resource("namespaces", None).create(
                    {"kind": "Namespace", "metadata": {"name": ns}})
            except Exception:
                pass  # AlreadyExists: the leader won the race

        def _quiesce_rv() -> int:
            _, rv = leader_c.pods("default").list_rv()
            for p in procs:
                if not p.call(("wait_rv", rv, 60.0)):
                    failures.append(f"{p.node_id} never replicated to "
                                    f"rv {rv} (stuck replica)")
            return rv

        def _leader_span() -> tuple:
            st = leader.call(("watch_stats",))
            return st["fanoutNs"], st["fanoutEvents"]

        # ---- phase A: leader-only fan-out baseline ----------------------
        rv0 = _quiesce_rv()
        a_leader = leader.call(("attach", "A", "Pod", leader_watchers, rv0))
        a_refs = sum(r.call(("attach", "A", "Pod", 1, rv0))["attached"]
                     for r in replicas)
        attached_a = a_leader["attached"] + a_refs
        sampler = _LagSampler([r.url for r in replicas]).start()
        ns0, ev0 = _leader_span()
        t0 = time.monotonic()
        _churn(spread, "storm-a", churn_pods)
        rv1 = _quiesce_rv()
        ns1, ev1 = _leader_span()
        span_a = (ns1 - ns0) / max(1, ev1 - ev0)
        result["phaseA"] = {
            "watchers": attached_a, "churn_s": round(
                time.monotonic() - t0, 2),
            "leaderSpanNsPerEvent": round(span_a, 1)}
        log(f"[watchstorm] phase A: {attached_a} leader-side watchers, "
            f"span {span_a:.0f} ns/event")

        # ---- phase B: the storm lands on the replicas -------------------
        per_replica = max(1, -(-(n_watchers - attached_a - 1)
                               // len(replicas)))
        b_replica = sum(r.call(("attach", "B", "Pod", per_replica, rv1),
                               timeout=300.0)["attached"]
                        for r in replicas)
        b_leader = leader.call(("attach", "B", "Pod", 1, rv1))["attached"]
        total = attached_a + b_replica + b_leader
        replica_share = (a_refs + b_replica) / total
        t0 = time.monotonic()
        _churn(spread, "storm-b", churn_pods)
        rv2 = _quiesce_rv()
        ns2, ev2 = _leader_span()
        span_b = (ns2 - ns1) / max(1, ev2 - ev1)
        result["phaseB"] = {
            "watchers": total, "replicaShare": round(replica_share, 3),
            "churn_s": round(time.monotonic() - t0, 2),
            "leaderSpanNsPerEvent": round(span_b, 1)}
        growth = span_b / max(span_a, 1.0)
        result["leaderSpanGrowth"] = round(growth, 3)
        log(f"[watchstorm] phase B: {total} watchers "
            f"({replica_share:.0%} replica-served), span {span_b:.0f} "
            f"ns/event, growth {growth:.2f}x")

        # ---- gap-free verification (before anything dies) ---------------
        gap_violations = severed = 0
        for cohort, expect in (("A", attached_a),
                               ("B", b_replica + b_leader)):
            sigs: dict = {}
            for p in procs:
                got = p.call(("collect", cohort), timeout=300.0)
                severed += got["severed"]
                for k, v in got["signatures"].items():
                    sigs[k] = sigs.get(k, 0) + v
            distinct, counted = len(sigs), sum(sigs.values())
            result[f"cohort{cohort}"] = {
                "watchers": counted, "distinctSignatures": distinct}
            if distinct != 1:
                gap_violations += distinct - 1
                failures.append(
                    f"cohort {cohort}: {distinct} distinct event "
                    f"signatures across {counted} watchers (gap or "
                    f"reorder somewhere): {list(sigs.items())[:4]}")
            if counted != expect:
                failures.append(f"cohort {cohort}: {counted} watchers "
                                f"reported, {expect} attached")
        drops = sum(p.call(("watch_stats",))["dropsTotal"] for p in procs)
        result["drops"] = drops
        result["severedStreams"] = severed
        # the staleness window closes BEFORE the disaster leg: the bound
        # is a promise about healthy replicas, and a SIGKILLed one is
        # supposed to go unready
        sampler.stop()
        result["staleness"] = {
            "maxReplayLagMs": round(sampler.max_lag_ms, 1),
            "samples": sampler.samples,
            "budgetMs": lag_budget_ms,
            "readyzFailures": sampler.readyz_failures}

        # ---- disaster leg: SIGKILL one replica mid-churn ----------------
        informer = SharedInformer(spread.pods("default")).start()
        if not informer.wait_for_cache_sync(30.0):
            failures.append("sentinel informer never synced")
        victim = replicas[0]
        heal_pods = [f"heal-{i}" for i in range(100)]
        from kubernetes_tpu.testing.wrappers import make_pod
        killed_at = None
        from kubernetes_tpu.client.clientset import ApiError
        for i, nm in enumerate(heal_pods):
            if i == len(heal_pods) // 3:
                victim.kill()
                killed_at = nm
            try:
                spread.pods("default").create(
                    make_pod(nm).obj().to_dict())
            except ApiError as e:
                if e.code != 409:  # retried-but-committed is a success
                    raise
        log(f"[watchstorm] killed {victim.node_id} at {killed_at}; "
            "churn continued through the outage")
        heal_s = victim.restart(ready_timeout=heal_slo_s)
        result["heal"] = {"replica": victim.node_id,
                          "readyz_s": round(heal_s, 2)}
        # readyz 200 means "caught up to the commit frontier I last saw";
        # pin the divergence check to the leader's CURRENT rv
        _, heal_rv = leader_c.pods("default").list_rv()
        if not victim.call(("wait_rv", heal_rv, 30.0)):
            failures.append(f"reborn {victim.node_id} never replicated "
                            f"to rv {heal_rv}")
        # zero loss: the spread-client informer converges to the exact
        # leader pod set despite its endpoint dying under it
        leader_names = {p["metadata"]["name"]
                        for p in leader_c.pods("default").list()}
        deadline = time.monotonic() + 60.0
        informer_names: set = set()
        while time.monotonic() < deadline:
            informer_names = {p["metadata"]["name"]
                              for p in informer.store.list()}
            if informer_names == leader_names:
                break
            time.sleep(0.25)
        missing = leader_names - informer_names
        phantom = informer_names - leader_names
        result["heal"]["informerMissing"] = len(missing)
        result["heal"]["informerPhantom"] = len(phantom)
        if missing or phantom:
            failures.append(
                f"informer lost events through the replica kill: "
                f"{len(missing)} missing (first {sorted(missing)[:3]}), "
                f"{len(phantom)} phantom")
        # the reborn replica snapshot-resynced to the same state
        reborn_names = {p["metadata"]["name"] for p in
                        HTTPClient(victim.url).pods("default").list()}
        if reborn_names != leader_names:
            failures.append(
                f"reborn {victim.node_id} diverges from the leader: "
                f"{len(leader_names ^ reborn_names)} differing pods")
        # publish the front-door ConfigMap once — ktpu status coverage
        FrontDoorPublisher(spread, endpoints).publish_once()

        # ---- gates (missing number = failure) ---------------------------
        if span_a <= 0 or span_b <= 0:
            failures.append("leader fan-out span missing — no events "
                            "were fanned during a measured phase")
        elif growth > span_growth_max:
            failures.append(f"leader fan-out span grew {growth:.2f}x "
                            f"under the storm (gate {span_growth_max}x)")
        if replica_share < min_replica_share:
            failures.append(f"replica-served share {replica_share:.2f} "
                            f"below {min_replica_share:.2f} — the storm "
                            "didn't land on the replicas")
        if total < min(n_watchers, 1000):
            failures.append(f"only {total} watchers attached "
                            f"(asked {n_watchers})")
        if drops:
            failures.append(f"{drops} slow-consumer drops during a storm "
                            "sized to fit every queue budget")
        if severed:
            failures.append(f"{severed} storm streams severed mid-storm")
        if sampler.samples == 0:
            failures.append("no replica lag samples collected — the "
                            "staleness bound went unmeasured")
        elif sampler.max_lag_ms > lag_budget_ms:
            failures.append(f"replica replay lag peaked at "
                            f"{sampler.max_lag_ms:.0f}ms "
                            f"(budget {lag_budget_ms:.0f}ms)")
        if sampler.readyz_failures:
            failures.append(f"{sampler.readyz_failures} /readyz probes "
                            "failed on replicas that were supposed to be "
                            "healthy (flap during the storm)")
        result["invariant_violations"] = (gap_violations + severed
                                          + drops + len(missing)
                                          + len(phantom))
    except Exception as e:  # a dead bench must fail loudly, not silently
        failures.append(f"bench crashed: {type(e).__name__}: {e}")
        result.setdefault("invariant_violations", None)
    finally:
        if sampler is not None and sampler._thread.is_alive():
            sampler.stop()
        if informer is not None:
            informer.stop()
        for p in procs:
            try:
                p.stop()
            except Exception:
                pass
    result["slo_failures"] = failures
    return result


if __name__ == "__main__":
    import json
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    _log = lambda *a: print(*a, file=sys.stderr)  # noqa: E731
    res = run_watch_storm(
        n_watchers=int(os.environ.get("BENCH_WATCHSTORM_WATCHERS",
                                      "10500")),
        churn_pods=int(os.environ.get("BENCH_WATCHSTORM_PODS", "600")),
        span_growth_max=float(os.environ.get(
            "BENCH_WATCHSTORM_SPAN_GROWTH", "1.2")),
        heal_slo_s=float(os.environ.get("BENCH_WATCHSTORM_HEAL_SLO",
                                        "90")),
        log=_log)
    print(json.dumps(res))
    if res.get("slo_failures") or res.get("invariant_violations"):
        sys.exit(1)
