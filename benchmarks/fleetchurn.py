"""FleetChurn: K tenant clusters driving sustained churn through ONE
scheduler process with ONE warm resident device program.

Each tenant is its own in-process apiserver + hollow-kubelet fleet (its own
resourceVersion space, its own node names — the real multi-cluster shape);
one ``FleetRunner`` (sched/fleet.py) serves all of them through the shared
drain pipeline. The noisy-neighbor leg: tenant 0 drives 4x the churn of its
siblings, and the per-tenant SLO gates prove nobody starves.

Hard gates (missing number = failure, PR-8 discipline):
  - every tenant's upfront pods bind 100%,
  - 0 invariant violations (fail-fast auditor live, cross_tenant included),
  - ONE warm program: steady-state resident-ctx rebuilds == 0 across the
    measured window — K tenants' churn folds into the same resident
    encoding without a single recompile,
  - per tenant: churn binds observed, completion ratio >= min_ratio, and
    bind p99 <= p99 ceiling — with tenant 0 churning 4x harder.
"""

from __future__ import annotations

import threading
import time


def _tenant_churn_loop(client, stop, period_s: float, stats: dict,
                       live_cap: int = 6) -> None:
    """One tenant's churn: create short-lived pods in namespace ``churn``,
    observe their bindings (poll-based latency), delete only BOUND pods
    (rolling window) so 100%-bind stays measurable. ``stats``: created /
    bound / latencies, read by the gate after the window closes."""
    import itertools

    from kubernetes_tpu.testing.wrappers import make_pod
    seq = itertools.count()
    created: dict[str, float] = {}   # name -> create ts (unbound)
    bound_live: list[str] = []
    while not stop.is_set():
        i = next(seq)
        try:
            name = f"fc-{i}"
            client.pods("churn").create(
                make_pod(name, "churn").req({"cpu": "50m"}).obj().to_dict())
            created[name] = time.time()
            stats["created"] = stats.get("created", 0) + 1
            # poll bindings (coarse; the p99 gate is in seconds)
            for p in client.pods("churn").list():
                nm = p["metadata"]["name"]
                if nm in created and (p.get("spec") or {}).get("nodeName"):
                    stats.setdefault("lat", []).append(
                        time.time() - created.pop(nm))
                    stats["bound"] = stats.get("bound", 0) + 1
                    bound_live.append(nm)
            while len(bound_live) > live_cap:
                client.pods("churn").delete(bound_live.pop(0))
        except Exception:
            pass  # churn is background noise; the gates own correctness
        stop.wait(period_s)
    stats["unbound_left"] = len(created)
    stats["pending_names"] = sorted(created)


def _drain_stragglers(client, stats: dict, grace_s: float) -> None:
    """Post-window grace: pods created right before the window closed get
    ``grace_s`` to bind before counting as starved."""
    deadline = time.time() + grace_s
    while stats.get("unbound_left", 0) and time.time() < deadline:
        try:
            still = set(stats.get("pending_names") or [])
            for p in client.pods("churn").list():
                nm = p["metadata"]["name"]
                if nm in still and (p.get("spec") or {}).get("nodeName"):
                    still.discard(nm)
                    stats["bound"] = stats.get("bound", 0) + 1
            stats["pending_names"] = sorted(still)
            stats["unbound_left"] = len(still)
        except Exception:
            pass
        time.sleep(0.3)


class _CompileCounter:
    """Counts REAL XLA backend compiles via jax.monitoring — the honest
    one-warm-program meter. A resident-ctx rebuild that re-encodes at the
    same bucket shapes reuses the compiled program and counts ZERO here;
    only a genuine recompile (bucket growth, new program variant) moves
    it."""

    def __init__(self):
        self.count = 0
        self._armed = False
        import jax
        jax.monitoring.register_event_duration_secs_listener(self._on)

    def _on(self, name, _dur, **_kw):
        if self._armed and "backend_compile" in name:
            self.count += 1

    def arm(self) -> None:
        self.count = 0
        self._armed = True

    def disarm(self) -> int:
        self._armed = False
        return self.count

    def wait_quiet(self, quiet_s: float, timeout_s: float) -> float:
        """Adaptive warm-up: block until ``quiet_s`` consecutive seconds
        pass with ZERO new compiles (all lazy program variants — fused
        patch, group path, wave buckets — have been exercised), or the
        timeout expires. Returns seconds waited. The steady-state window
        opens AFTER this, so the 0-recompiles gate judges the warm
        program, not the warm-up race."""
        self.arm()
        t0 = time.time()
        last, last_change = self.count, time.time()
        while time.time() - t0 < timeout_s:
            time.sleep(0.25)
            if self.count != last:
                last, last_change = self.count, time.time()
            elif time.time() - last_change >= quiet_s:
                break
        self._armed = False
        return time.time() - t0


def _p99(lat: list) -> float:
    if not lat:
        return 0.0
    s = sorted(lat)
    return round(s[min(len(s) - 1, int(0.99 * len(s)))], 3)


def run_fleet_churn(n_tenants: int = 4, nodes_per_tenant: int = 8,
                    upfront_pods: int = 12, batch_size: int = 8,
                    max_drain_batches: int = 0, window_s: float = 12.0,
                    warmup_s: float = 8.0, churn_period_s: float = 0.4,
                    noisy_factor: int = 4, bind_timeout: float = 120.0,
                    p99_slo_s: float = 10.0, min_ratio: float = 0.5,
                    heartbeat_period: float = 5.0,
                    log=lambda *a: None) -> dict:
    from benchmarks.connected import _audit_close, _bench_auditor
    from kubernetes_tpu.client.clientset import HTTPClient
    from kubernetes_tpu.config.types import SchedulerConfiguration
    from kubernetes_tpu.kubelet.kubemark import HollowCluster
    from kubernetes_tpu.sched.fleet import FleetRunner
    from kubernetes_tpu.store.apiserver import APIServer
    from kubernetes_tpu.testing.wrappers import make_pod

    K = max(1, int(n_tenants))
    # one compiled drain width must cover one block per active tenant
    B = max_drain_batches or max(2, K)
    servers: list = []
    clusters: list = []
    runner = None
    failures: list[str] = []
    result: dict = {"case": "FleetChurn",
                    "workload": f"{K}tenants_{nodes_per_tenant}n_"
                                f"{upfront_pods}p_noisy{noisy_factor}x",
                    "tenants": K, "nodes_per_tenant": nodes_per_tenant,
                    "window_s": window_s, "noisy_factor": noisy_factor}
    try:
        t0 = time.time()
        servers = [APIServer().start() for _ in range(K)]
        clients = [HTTPClient(s.url, timeout=120.0) for s in servers]
        clusters = [HollowCluster(HTTPClient(s.url, timeout=120.0),
                                  nodes_per_tenant, prefix=f"fc{t}",
                                  heartbeat_period=heartbeat_period,
                                  drivers=2).start(wait_sync=60.0)
                    for t, s in enumerate(servers)]
        result["register_s"] = round(time.time() - t0, 2)
        log(f"  {K} tenant apiservers + {K * nodes_per_tenant} hollow "
            f"nodes up in {result['register_s']}s")

        runner = FleetRunner(
            [HTTPClient(s.url) for s in servers],
            SchedulerConfiguration(batch_size=batch_size,
                                   max_drain_batches=B))
        runner.auditor = _bench_auditor(runner, runner.client)
        runner.start(wait_sync=60.0)

        # arm the resident drain context + fused-fold variants at the
        # window's shapes (the connected bench's warm discipline): sample
        # pods are fleet-keyed so the tenant plane is in the warm shapes
        from kubernetes_tpu.api.types import Pod as _Pod
        from kubernetes_tpu.sched.fleet import rekey_for_tenant
        warm_pods = [_Pod.from_dict(rekey_for_tenant(
            t % K, "pods",
            make_pod(f"warm-{t}", "default").req({"cpu": "50m"})
            .obj().to_dict())) for t in range(batch_size * B)]
        armed = runner.scheduler.warm_drain(
            warm_pods, slot_headroom=K * upfront_pods + batch_size * B + 64)
        # the GROUP path (gang_converge) serves any cycle whose resident
        # ctx just died to a capacity rebuild — compile it now, at the
        # exact static-arg signature _schedule_group uses, so a mid-window
        # rebuild can never cost a compile
        from kubernetes_tpu.models.gang import gang_schedule
        profile = runner.cfg.profiles[0]
        nodes_w, ct_w, meta_w = runner.cache.snapshot(
            pending_pods=warm_pods[:batch_size])
        pb_w = runner.cache.encode_pods(warm_pods[:batch_size], meta_w,
                                        min_p=batch_size)
        gang_schedule(ct_w, pb_w, seed=runner.cfg.seed,
                      fit_strategy=profile.fit_strategy,
                      topo_keys=meta_w.topo_keys, serial=False,
                      max_rounds=runner.cfg.max_gang_rounds,
                      weights=profile.weights(),
                      enabled_filters=profile.enabled_filters,
                      plugins=runner.scheduler.registry.tensor_plugins(
                          None if profile.out_of_tree is None
                          else set(profile.out_of_tree)))
        log(f"  drain+group warm (ctx armed: {armed})")

        # ---- upfront bind leg: every tenant, 100% ------------------------
        t_bind = time.time()
        for c in clients:
            c.pods("default").create_many(
                [make_pod(f"up-{i}", "default").req({"cpu": "100m"})
                 .obj().to_dict() for i in range(upfront_pods)])
        deadline = t_bind + bind_timeout
        per_bound = [0] * K
        while time.time() < deadline:
            per_bound = [sum(1 for p in c.pods("default").list()
                             if p["spec"].get("nodeName")) for c in clients]
            if all(b >= upfront_pods for b in per_bound):
                break
            time.sleep(0.4)
        result["upfront_bound"] = per_bound
        result["upfront_bind_s"] = round(time.time() - t_bind, 2)
        log(f"  upfront: {per_bound} bound in {result['upfront_bind_s']}s")
        for t, b in enumerate(per_bound):
            if b < upfront_pods:
                failures.append(f"tenant {t}: only {b}/{upfront_pods} "
                                "upfront pods bound")

        # ---- churn window: tenant 0 drives noisy_factor x ----------------
        churn_stop = threading.Event()
        stats: list[dict] = [{} for _ in range(K)]
        threads = []
        for t in range(K):
            period = churn_period_s / (noisy_factor if t == 0 else 1)
            th = threading.Thread(
                target=_tenant_churn_loop,
                args=(HTTPClient(servers[t].url, timeout=60.0), churn_stop,
                      period, stats[t]), daemon=True)
            th.start()
            threads.append(th)
        compiles = _CompileCounter()
        time.sleep(warmup_s)  # churn reaches its steady live level
        # adaptive warm-up tail: the window opens only after 4 quiet
        # seconds with zero compiles — lazy variants (first fused patch,
        # group-path bucket crossings) must land in warm-up, not the gate
        result["warmup_quiet_s"] = round(
            compiles.wait_quiet(quiet_s=4.0, timeout_s=45.0), 1)
        ctx0 = dict(runner.scheduler.ctx_stats)
        enc0 = runner.cache.stats().get("full_encodes", 0)
        for s_ in stats:
            s_["created"] = s_["bound"] = 0
            s_["lat"] = []
        compiles.arm()
        time.sleep(window_s)
        xla_compiles = compiles.disarm()
        ctx1 = dict(runner.scheduler.ctx_stats)
        enc1 = runner.cache.stats().get("full_encodes", 0)
        churn_stop.set()
        for th in threads:
            th.join(timeout=10.0)
        for t in range(K):
            _drain_stragglers(clients[t], stats[t], grace_s=15.0)

        # ---- one-warm-program assertion ----------------------------------
        # "0 steady-state recompiles" means ZERO XLA backend compiles in
        # the measured window: K tenants' churn runs entirely on warm
        # compiled programs. Resident-ctx rebuilds at unchanged bucket
        # shapes (capacity-driven re-encodes on a tiny fold region) reuse
        # the compiled program and are recorded but not gated.
        rebuilds = ctx1.get("rebuilds", 0) - ctx0.get("rebuilds", 0)
        folds = ctx1.get("folds", 0) - ctx0.get("folds", 0)
        patches = ctx1.get("patches", 0) - ctx0.get("patches", 0)
        ctx_live = runner.scheduler._drain_ctx is not None
        result["ctx_window"] = {
            "xla_compiles": xla_compiles,
            "rebuilds": rebuilds, "folds": folds, "patches": patches,
            "full_encodes": enc1 - enc0,
            "resident_ctx_live": ctx_live,
            "rebuild_reasons": dict(ctx1.get("reasons") or {}),
        }
        if xla_compiles != 0:
            failures.append(
                f"one-warm-program violated: {xla_compiles} XLA "
                f"compile(s) during the steady-state window")

        # ---- per-tenant SLO gates ----------------------------------------
        tenants_out = {}
        for t in range(K):
            s_ = stats[t]
            created = s_.get("created", 0)
            bound = s_.get("bound", 0)
            left = s_.get("unbound_left", 0)
            p99 = _p99(s_.get("lat") or [])
            ratio = (bound / created) if created else None
            tenants_out[str(t)] = {
                "noisy": t == 0, "created": created, "bound": bound,
                "unbound": left, "binds_per_s": round(bound / window_s, 2),
                "p99_bind_s": p99, "ratio": (round(ratio, 3)
                                             if ratio is not None else None)}
            if created <= 0:
                failures.append(f"tenant {t}: churn created NOTHING — "
                                "the gate cannot pass silently")
                continue
            if left:
                failures.append(f"tenant {t}: {left} churn pod(s) never "
                                "bound (starved)")
            if ratio is None or ratio < min_ratio:
                failures.append(f"tenant {t}: bind ratio {ratio} below "
                                f"the {min_ratio} floor")
            if not s_.get("lat"):
                failures.append(f"tenant {t}: no bind latencies observed")
            elif p99 > p99_slo_s:
                failures.append(f"tenant {t}: bind p99 {p99}s above the "
                                f"{p99_slo_s}s ceiling")
        result["tenant"] = tenants_out
        result["fleet_sched"] = runner.fleet_sched_status()
        result.update(_audit_close(runner))
        if result.get("invariant_violations") is None:
            failures.append("invariant_violations missing")
    finally:
        try:
            if runner is not None:
                runner.stop()
        except Exception:
            pass
        for cl in clusters:
            try:
                cl.stop()
            except Exception:
                pass
        for s in servers:
            try:
                s.stop()
            except Exception:
                pass
    result["slo_failures"] = failures
    return result


if __name__ == "__main__":
    import json
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    res = run_fleet_churn(
        n_tenants=int(os.environ.get("BENCH_FLEET_TENANTS", "4")),
        nodes_per_tenant=int(os.environ.get("BENCH_FLEET_NODES", "8")),
        upfront_pods=int(os.environ.get("BENCH_FLEET_PODS", "12")),
        window_s=float(os.environ.get("BENCH_FLEET_WINDOW_S", "12")),
        noisy_factor=int(os.environ.get("BENCH_FLEET_NOISY", "4")),
        p99_slo_s=float(os.environ.get("BENCH_FLEET_P99", "10")),
        log=lambda *a: print(*a, file=sys.stderr))
    print(json.dumps(res))
    if res.get("slo_failures") or res.get("invariant_violations"):
        sys.exit(1)
