"""ScaleFleet: a two-point hollow-fleet sweep proving the control plane
is SUBLINEAR in fleet size.

PR 11 made the device program effectively free; what remains of a
ConnectedMesh leg at fleet scale is the hollow fleet's own control-plane
traffic — heartbeats, node leases, pod status. This case registers a
hollow fleet at two sizes (default sized to the box; the 100k campaign
tier runs ``BENCH_SCALE_NODES="1250 10000"``), drives sustained churn
through the one resident scheduler program, and measures the combined
``kubelet/heartbeat`` + ``kubelet/lease_renew`` + ``kubemark/status_flush``
span time over an identical steady-state window at each size.

Hard gate (the PR-8 SLO discipline): with the bulk fan-in paths
(``nodes/-/status``, ``leases/-/renew``, sharded batchers) the combined
control-plane span must grow <= ``max_growth`` (default 2x) while the
fleet grows ``fleet_sizes[-1]/fleet_sizes[0]`` (default 8x) — and a
MISSING span is a failure, never a silent pass. The fail-fast invariant
auditor is live for every leg.
"""

from __future__ import annotations

import multiprocessing as mp
import time
import urllib.request

# the control-plane spans the sublinear gate sums (missing = failure)
CONTROL_PLANE_SPANS = ("kubelet/heartbeat", "kubelet/lease_renew",
                       "kubemark/status_flush")


def _bulk_request_counts(url: str) -> dict:
    """apiserver_bulk_requests_total{endpoint=...} from the apiserver
    subprocess's /metrics — attributes how much of the leg's fan-in rode
    bulk endpoints (the store-side counter lives in the server process)."""
    out: dict = {}
    try:
        with urllib.request.urlopen(url + "/metrics", timeout=10.0) as resp:
            for line in resp.read().decode().splitlines():
                if line.startswith("apiserver_bulk_requests_total{"):
                    label, _, val = line.rpartition(" ")
                    ep = label.split('endpoint="', 1)[-1].split('"')[0]
                    out[ep] = float(val)
    except Exception:
        pass  # metrics are attribution, not the gate
    return out


def _pod_churn_loop(client, stop, period_s: float = 0.1,
                    counter=None) -> None:
    """Sustained POD churn (namespace ``churn``): create/delete a rolling
    window of short-lived pods the scheduler binds onto the hollow fleet
    and the kubelets drive to Running (status traffic). Deliberately NO
    node churn: pod deltas ride the one resident scheduler program as
    fused folds, while a node add/delete forces a full O(fleet) cluster
    re-encode per op — that is the scheduler's scaling story, and letting
    it peg the GIL here would charge its cost to the control-plane spans
    this case gates on."""
    import itertools

    from kubernetes_tpu.testing.wrappers import make_pod
    seq = itertools.count()
    live: list = []
    while not stop.is_set():
        i = next(seq)
        try:
            pod = make_pod(f"churn-p{i}", "churn").req(
                {"cpu": "100m"}).obj()
            client.pods("churn").create(pod.to_dict())
            live.append(pod.metadata.name)
            if len(live) > 3:
                client.pods("churn").delete(live.pop(0))
            if counter is not None:
                counter["ops"] = counter.get("ops", 0) + 2
        except Exception:
            pass  # churn is background noise; the bench owns correctness
        stop.wait(period_s)


def _run_leg(n_hollow: int, n_pods: int, batch_size: int,
             heartbeat_period: float, window_s: float, n_windows: int,
             churn_period_s: float, timeout: float, log) -> dict:
    import threading

    from benchmarks.connected import (_audit_close, _bench_auditor,
                                      _serve, _span_totals, _trace_window)
    from kubernetes_tpu.client.clientset import HTTPClient
    from kubernetes_tpu.config.types import SchedulerConfiguration
    from kubernetes_tpu.kubelet.kubemark import HollowCluster
    from kubernetes_tpu.sched.runner import SchedulerRunner
    from kubernetes_tpu.testing.wrappers import make_pod

    ctx = mp.get_context("spawn")
    parent, child = ctx.Pipe()
    server = ctx.Process(target=_serve, args=(child,), daemon=True)
    server.start()
    port = parent.recv()
    url = f"http://127.0.0.1:{port}"
    cluster = runner = None
    leg: dict = {"nodes": n_hollow, "pods": n_pods}
    try:
        t0 = time.time()
        cluster = HollowCluster(HTTPClient(url, timeout=120.0), n_hollow,
                                prefix=f"sf{n_hollow}",
                                heartbeat_period=heartbeat_period
                                ).start(wait_sync=60.0)
        leg["register_s"] = round(time.time() - t0, 2)
        log(f"  {n_hollow} hollow nodes registered in "
            f"{leg['register_s']}s")

        runner = SchedulerRunner(
            HTTPClient(url),
            SchedulerConfiguration(batch_size=batch_size,
                                   max_drain_batches=2))
        runner.auditor = _bench_auditor(runner, HTTPClient(url))
        runner.start(wait_sync=60.0)

        client = HTTPClient(url, timeout=120.0)
        pods = [make_pod(f"sf-{i}", "default")
                .req({"cpu": "100m", "memory": "64Mi"}).obj().to_dict()
                for i in range(n_pods)]
        t_bind = time.time()
        client.pods("default").create_many(pods)
        deadline = t_bind + timeout
        bound = 0
        while time.time() < deadline:
            bound = sum(1 for p in client.pods("default").list()
                        if p["spec"].get("nodeName"))
            if bound >= n_pods:
                break
            time.sleep(0.5)
        leg["bound"] = bound
        leg["bind_s"] = round(time.time() - t_bind, 2)
        log(f"  {bound}/{n_pods} bound at +{leg['bind_s']}s")

        # steady state: identical wall-clock window at every fleet size —
        # the churn load is size-INDEPENDENT, so whatever grows between
        # legs is the fleet's own control-plane traffic. Churn warms up
        # BEFORE the window opens: the first churn nodes/pods grow encode
        # buckets and trigger the leg's last JIT recompiles, which must
        # not be charged to either leg's measured spans.
        churn_stop = threading.Event()
        churn_stats: dict = {}
        threading.Thread(target=_pod_churn_loop,
                         args=(HTTPClient(url), churn_stop),
                         kwargs={"counter": churn_stats,
                                 "period_s": churn_period_s},
                         daemon=True).start()
        time.sleep(6.0)  # churn warm-up (outside the measured window)
        churn_stats["ops"] = 0
        # min-of-K windows: the spans are WALL time in a process whose one
        # core also runs the scheduler's device program, so a flush that
        # lands while a dispatch holds the GIL reads 2-3x its true cost.
        # That contamination is strictly ADDITIVE, so the minimum across
        # identical consecutive windows is the honest estimator of what
        # the control plane itself costs (the timeit-min discipline).
        windows: list[dict] = []
        for _ in range(n_windows):
            _trace_window()
            time.sleep(window_s)
            windows.append(_span_totals())
        spans = windows[-1]
        churn_stop.set()
        leg["window_s"] = window_s
        leg["windows"] = [{k: w.get(k) for k in CONTROL_PLANE_SPANS}
                          for w in windows]
        leg["span_ms"] = spans
        cp: dict = {}
        for k in CONTROL_PLANE_SPANS:
            seen = [w.get(k) for w in windows
                    if isinstance(w.get(k), (int, float)) and w.get(k) > 0]
            cp[k] = min(seen) if seen else None  # absent everywhere = None
        leg["control_plane_ms"] = cp
        leg["churn_api_ops"] = churn_stats.get("ops", 0)
        leg["fleet"] = cluster.fleet_stats()
        leg["bulk_requests"] = _bulk_request_counts(url)
        leg.update(_audit_close(runner))
        return leg
    finally:
        try:
            if runner is not None:
                runner.stop()
            if cluster is not None:
                cluster.stop()
        except Exception:
            pass
        try:
            parent.send("stop")
        except Exception:
            pass
        server.join(timeout=5.0)
        if server.is_alive():
            server.terminate()


def run_scale_fleet(fleet_sizes=(256, 2048), n_pods: int = 256,
                    batch_size: int = 256, heartbeat_period: float = 5.0,
                    window_s: float = 12.0, n_windows: int = 3,
                    churn_period_s: float = 0.5,
                    max_growth: float = 2.0, timeout: float = 240.0,
                    log=lambda *a: None) -> dict:
    sizes = sorted(int(s) for s in fleet_sizes)
    legs = []
    for n in sizes:
        log(f"  ScaleFleet leg: {n} hollow nodes ...")
        legs.append(_run_leg(n, n_pods, batch_size, heartbeat_period,
                             window_s, n_windows, churn_period_s,
                             timeout, log))

    result: dict = {
        "case": "ScaleFleet",
        "workload": "x".join(str(n) for n in sizes)
                    + f"hollow_{n_pods}pods",
        "fleet_sizes": sizes,
        "heartbeat_period_s": heartbeat_period,
        "window_s": window_s,
        "windows_per_leg": n_windows,
        "max_growth": max_growth,
        "legs": legs,
        "invariant_violations": sum(
            int(leg.get("invariant_violations") or 0) for leg in legs),
    }

    # ---- the sublinear gate (missing number = failure) -------------------
    failures: list[str] = []
    totals = []
    for leg in legs:
        total = 0.0
        for k in CONTROL_PLANE_SPANS:
            v = (leg.get("control_plane_ms") or {}).get(k)
            if not isinstance(v, (int, float)):
                failures.append(
                    f"{leg['nodes']}-node leg: span {k!r} missing — the "
                    "gate cannot pass silently")
                v = 0.0
            total += v
        totals.append(round(total, 1))
        if leg.get("bound", 0) < n_pods:
            failures.append(f"{leg['nodes']}-node leg: only "
                            f"{leg.get('bound', 0)}/{n_pods} pods bound")
    result["control_plane_totals_ms"] = dict(zip(
        (str(n) for n in sizes), totals))
    if len(sizes) < 2:
        # a one-leg "sweep" has no growth factor — and a silently absent
        # figure must never read as a pass (the BENCH_r05 lesson)
        failures.append(
            f"fleet sweep needs >= 2 sizes to gate growth (got {sizes})")
    if len(totals) >= 2 and not failures:
        small, big = totals[0], totals[-1]
        if small <= 0:
            failures.append("smallest leg recorded 0 control-plane span "
                            "ms — nothing measured, refusing to pass")
        else:
            growth = round(big / small, 3)
            result["growth_factor"] = growth
            result["size_growth"] = round(sizes[-1] / sizes[0], 2)
            if growth > max_growth:
                failures.append(
                    f"control-plane span grew {growth}x for a "
                    f"{result['size_growth']}x fleet (gate {max_growth}x)")
    result["slo_failures"] = failures
    return result


if __name__ == "__main__":
    import json
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    sizes = [int(t) for t in os.environ.get(
        "BENCH_SCALE_NODES", "256 2048").replace(",", " ").split()]
    res = run_scale_fleet(
        fleet_sizes=sizes,
        n_pods=int(os.environ.get("BENCH_SCALE_PODS", "256")),
        window_s=float(os.environ.get("BENCH_SCALE_WINDOW_S", "12")),
        heartbeat_period=float(os.environ.get("BENCH_SCALE_HB_PERIOD",
                                              "5.0")),
        max_growth=float(os.environ.get("BENCH_SCALE_MAX_GROWTH", "2.0")),
        log=lambda *a: print(*a, file=sys.stderr))
    print(json.dumps(res))
    if res.get("slo_failures") or res.get("invariant_violations"):
        sys.exit(1)
