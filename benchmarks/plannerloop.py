"""PlannerLoop: three planners, one cluster image — the steady-window proof.

One scheduler process holds the device-resident cluster encoding; one
``BackgroundPlanner`` cadence drives the autoscaler's scale-up/scale-down
simulation, the descheduler's eviction planning, and gang defrag against it
every cycle through the shared ``ResidentPlanner`` overlay views.

Hard gates (missing number = failure, PR-8 discipline):
  - ZERO XLA compiles across the measured window (``jax.monitoring``
    backend_compile events, adaptive warmup so lazy variants land before
    the gate arms),
  - zero cold full encodes: the resident decline delta across the window
    is 0 AND the scheduler cache's ``full_encodes`` counter does not move,
  - every planner's overlay hit count ADVANCES in the window (the zero
    above is not vacuous — all three planners really ride the image),
  - resident-vs-cold parity: the same observation planned through the
    overlay view and through today's cold encode path produces bit-equal
    plans (scale-up options, scale-down proof, eviction sets, gang moves),
  - 0 invariant violations under the fail-fast auditor.

Run standalone (``python -m benchmarks.plannerloop``) or via ``bench.py``
with ``BENCH_PLANNER=1``. ``BENCH_PLANNER_DATA_DIR`` runs the apiserver in
durable mode so the run's ``wal.jsonl`` can be converted into a committed
scenario trace (``trace_from_wal``).
"""

from __future__ import annotations

import time


def _norm_scale_up(options) -> list:
    return [(o.group.name, sorted(o.pod_indices), o.nodes_needed,
             round(float(o.waste), 9)) for o in options]


def _norm_scale_down(plan) -> tuple:
    return (sorted(plan.removable),
            {n: sorted(m) for n, m in plan.placements.items()},
            dict(plan.blocked))


def _norm_evictions(plan) -> tuple:
    return ([(s.name, s.strategy, sorted(p.key for p in s.victims),
              sorted(s.moves), s.reason) for s in plan.accepted],
            dict(plan.blocked), plan.batch_victims, plan.batch_sets)


def _norm_gang(plan) -> tuple:
    acc = None
    if plan.accepted is not None:
        acc = (plan.accepted.name, plan.accepted.strategy,
               sorted(p.key for p in plan.accepted.victims),
               sorted(plan.accepted.moves))
    return (plan.gang, acc, sorted(plan.gang_moves),
            plan.fits_without_evictions, dict(plan.blocked))


def run_planner_loop(n_nodes: int = 8, pods_per_node: int = 3,
                     window_cycles: int = 6, max_warmup_cycles: int = 14,
                     quiet_cycles: int = 2, bind_timeout: float = 120.0,
                     data_dir=None, log=lambda *a: None) -> dict:
    from benchmarks.connected import _audit_close, _bench_auditor
    from kubernetes_tpu.autoscaler.autoscaler import ClusterAutoscaler
    from kubernetes_tpu.autoscaler.nodegroup import (
        NODE_GROUP_LABEL, NodeGroup, StaticNodeGroupProvider)
    from kubernetes_tpu.autoscaler.simulator import (
        simulate_scale_down, simulate_scale_up)
    from kubernetes_tpu.client.clientset import HTTPClient
    from kubernetes_tpu.config.types import SchedulerConfiguration
    from kubernetes_tpu.descheduler.descheduler import (
        Descheduler, DeschedulerConfiguration)
    from kubernetes_tpu.descheduler.strategies import GANG_LABEL
    from kubernetes_tpu.sched.bgplanner import BackgroundPlanner
    from kubernetes_tpu.sched.runner import SchedulerRunner
    from kubernetes_tpu.store.apiserver import APIServer
    from kubernetes_tpu.testing.wrappers import make_node, make_pod

    server = None
    runner = None
    failures: list[str] = []
    result: dict = {"case": "PlannerLoop",
                    "workload": f"{n_nodes}n_{pods_per_node}ppn_"
                                f"{window_cycles}cyc"}
    try:
        t0 = time.time()
        server = APIServer(data_dir=data_dir).start()
        client = HTTPClient(server.url, timeout=120.0)

        # static fleet, all nodes group-labeled (scale-down candidates via
        # re-adoption); pl-n0 carries ONE small pod so it sits under both
        # the descheduler's HighNodeUtilization threshold (a persistent
        # candidate set every dry-run cycle) and the autoscaler's
        # scale-down threshold (a live removable-node proof every cycle)
        client.nodes().create_many(
            [make_node(f"pl-n{i}")
             .capacity({"cpu": "8", "memory": "32Gi", "pods": "32"})
             .label(NODE_GROUP_LABEL, "pool-a").obj().to_dict()
             for i in range(n_nodes)])
        bound = [make_pod("pl-b0-0", "default")
                 .req({"cpu": "1", "memory": "1Gi"})
                 .node("pl-n0").obj().to_dict()]
        for i in range(1, n_nodes):
            for j in range(pods_per_node):
                bound.append(make_pod(f"pl-b{i}-{j}", "default")
                             .req({"cpu": "2", "memory": "2Gi"})
                             .node(f"pl-n{i}").obj().to_dict())
        client.pods("default").create_many(bound)

        runner = SchedulerRunner(
            HTTPClient(server.url),
            SchedulerConfiguration(batch_size=8, max_drain_batches=1))
        runner.auditor = _bench_auditor(runner, client)
        # no drain loop: the fleet is static, every planner cycle must see
        # a fresh resident image with nothing in flight
        runner.start(wait_sync=60.0, start_loop=False)
        t1 = time.time()
        armed = runner.scheduler.warm_drain(
            [make_pod(f"pl-w{k}", "default").req({"cpu": "2"}).obj()
             for k in range(8)],
            slot_headroom=len(bound) + 64)
        result["seed_s"] = round(t1 - t0, 2)
        log(f"  {n_nodes} nodes + {len(bound)} bound pods in "
            f"{result['seed_s']}s (ctx armed: {armed})")

        # the perpetual planning workload: pods nothing (node or template)
        # can absorb keep the scale-up simulation live every cycle, and a
        # pending gang keeps gang defrag re-planning (descheduler dry-run,
        # so nothing ever executes and the image never churns)
        client.pods("default").create_many(
            [make_pod(f"pl-big{k}", "default")
             .req({"cpu": "64", "memory": "128Gi"}).obj().to_dict()
             for k in range(2)])
        client.pods("default").create_many(
            [make_pod(f"pl-g{k}", "default").req({"cpu": "6"})
             .label(GANG_LABEL, "pl-gang").obj().to_dict()
             for k in range(3)])

        groups = [
            NodeGroup(name="pool-a", min_size=0, max_size=n_nodes + 4,
                      template=make_node("pool-a-template").capacity(
                          {"cpu": "2", "memory": "4Gi", "pods": "16"}).obj()),
            # headroom 0: never provisioned by the loop, but the parity leg
            # hands simulate_scale_up room so a REAL option gets compared
            NodeGroup(name="pool-big", min_size=0, max_size=0,
                      template=make_node("pool-big-template").capacity(
                          {"cpu": "96", "memory": "256Gi",
                           "pods": "32"}).obj()),
        ]
        autoscaler = ClusterAutoscaler(
            HTTPClient(server.url, timeout=60.0),
            StaticNodeGroupProvider(HTTPClient(server.url, timeout=60.0),
                                    groups),
            utilization_threshold=0.5,
            scale_down_unneeded_s=10 ** 9)   # plan every cycle, reclaim never
        descheduler = Descheduler(
            HTTPClient(server.url, timeout=60.0),
            DeschedulerConfiguration())
        planner = BackgroundPlanner(
            client, runner.scheduler, autoscaler=autoscaler,
            descheduler=descheduler, descheduler_dry_run=True,
            warmup_cycles=1)

        # ---- adaptive warmup: cycle until the compile gate stays quiet ----
        t2 = time.time()
        quiet = 0
        warm_used = 0
        while warm_used < max_warmup_cycles and quiet < quiet_cycles:
            s = planner.run_once()
            warm_used += 1
            quiet = quiet + 1 if s.get("steadyCompiles", 1) == 0 else 0
        result["warmup_cycles"] = warm_used
        result["warmup_s"] = round(time.time() - t2, 2)
        log(f"  warmup: {warm_used} cycles in {result['warmup_s']}s "
            f"({quiet} quiet)")
        if quiet < quiet_cycles:
            failures.append(
                f"warmup never went compile-quiet in {warm_used} cycles")

        # ---- measured window ---------------------------------------------
        stats0 = planner.resident.stats()
        enc0 = runner.cache.stats().get("full_encodes", 0)
        compiles = 0
        t3 = time.time()
        for _ in range(window_cycles):
            s = planner.run_once()
            compiles += s.get("steadyCompiles", 0)
        result["window_s"] = round(time.time() - t3, 2)
        result["cycle_ms"] = round(1000 * (time.time() - t3)
                                   / window_cycles, 1)
        stats1 = planner.resident.stats()
        result["window_compiles"] = compiles
        if compiles:
            failures.append(f"{compiles} XLA compiles in the steady window")
        declines = (sum(sum(v.values())
                        for v in stats1["declines"].values())
                    - sum(sum(v.values())
                          for v in stats0["declines"].values()))
        result["window_declines"] = declines
        if declines:
            result["decline_reasons"] = stats1["declines"]
            failures.append(f"{declines} resident declines (cold encodes) "
                            "in the steady window")
        enc_delta = runner.cache.stats().get("full_encodes", 0) - enc0
        result["window_full_encodes"] = enc_delta
        if enc_delta:
            failures.append(f"{enc_delta} scheduler cold full encodes "
                            "in the steady window")
        hits = {}
        for name in ("autoscaler", "descheduler", "gangDefrag"):
            d = (stats1["hits"].get(name, 0) - stats0["hits"].get(name, 0))
            hits[name] = d
            if d <= 0:
                failures.append(f"planner {name}: overlay hits did not "
                                f"advance in the window ({d})")
        result["window_hits"] = hits
        result["spans_s"] = {k: round(v, 4)
                             for k, v in planner._spans.items()}
        log(f"  window: {window_cycles} cycles, {compiles} compiles, "
            f"{declines} declines, hits {hits}")

        # ---- resident-vs-cold parity (same observation, both paths) ------
        nodes_o, pods_o, pod_dicts_o = autoscaler._observe()
        bound_o = [p for p in pods_o if p.spec.node_name]
        pending_o = autoscaler._pending(pods_o)
        headroom = {"pool-a": 4, "pool-big": 2}  # force a real option
        up = [_norm_scale_up(simulate_scale_up(
            nodes_o, bound_o, pending_o, groups, headroom=headroom,
            encoder=autoscaler.encoder, resident=r))
            for r in (planner.resident, None)]
        candidates = [n.metadata.name for n in nodes_o]
        down = [_norm_scale_down(simulate_scale_down(
            nodes_o, bound_o, candidates, utilization_threshold=0.5,
            all_pod_dicts=pod_dicts_o, encoder=autoscaler.encoder,
            resident=r)) for r in (planner.resident, None)]
        obs = descheduler._observe()
        dplans = []
        for r in (planner.resident, None):
            descheduler.resident = r
            ep, gps = descheduler.plan(*obs)
            dplans.append((_norm_evictions(ep),
                           [_norm_gang(g) for g in gps]))
        descheduler.resident = planner.resident
        parity = {"scale_up": up[0] == up[1], "scale_down": down[0] == down[1],
                  "evictions": dplans[0][0] == dplans[1][0],
                  "gang_defrag": dplans[0][1] == dplans[1][1]}
        result["plan_parity"] = parity
        result["parity_scale_up_options"] = len(up[1])
        result["parity_gang_plans"] = len(dplans[1][1])
        for leg, ok in parity.items():
            if not ok:
                failures.append(f"resident/cold plan divergence: {leg}")
        if not up[1]:
            failures.append("parity scale-up produced no options "
                            "(vacuous comparison)")
        log(f"  parity: {parity} ({len(up[1])} scale-up options, "
            f"{len(dplans[1][1])} gang plans)")

        result["planner_status"] = planner.status()
        result["overlay"] = stats1
        if data_dir:
            import os
            # retire the perpetually-pending planning workload so the
            # captured WAL converts to a replayable trace: a scenario
            # replay gates 100% binding on pods the trace leaves resident
            for k in range(2):
                client.pods("default").delete(f"pl-big{k}")
            for k in range(3):
                client.pods("default").delete(f"pl-g{k}")
            result["wal_path"] = os.path.join(data_dir, "wal.jsonl")
    finally:
        try:
            if runner is not None:
                result.update(_audit_close(runner))
        finally:
            if server is not None:
                server.stop()
    if "invariant_violations" not in result:
        result["invariant_violations"] = None
        failures.append("no invariant audit ran")
    result["slo_failures"] = failures
    return result


if __name__ == "__main__":
    import json
    import os
    import sys

    res = run_planner_loop(
        n_nodes=int(os.environ.get("BENCH_PLANNER_NODES", "8")),
        window_cycles=int(os.environ.get("BENCH_PLANNER_CYCLES", "6")),
        data_dir=os.environ.get("BENCH_PLANNER_DATA_DIR") or None,
        log=lambda *a: print(*a, file=sys.stderr, flush=True))
    print(json.dumps(res, indent=2, default=str))
    if res.get("slo_failures") or res.get("invariant_violations"):
        sys.exit(1)
