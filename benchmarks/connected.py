"""Connected-path benchmark: SchedulerRunner against a SEPARATE-PROCESS
apiserver.

The raw gang numbers (scheduler_perf.py) measure the device program alone;
this measures the PRODUCT — informers watching the apiserver over HTTP, the
scheduling queue, the cache's incremental encode, the device-resident fused
drain, and bulk binding POSTs — in the reference's deployment shape: the
apiserver and the scheduler are separate processes (separate binaries
upstream), so API serving and watch fan-out do not share the scheduler's
interpreter. The measured window matches upstream scheduler_perf's
``createPods`` op: scheduler running and synced, clock starts at pod
creation, stops when the last binding is visible in the store.
"""

from __future__ import annotations

import multiprocessing as mp
import time


def _trace_window() -> None:
    """Arm the process-global tracer for a measured window."""
    from kubernetes_tpu.utils.tracing import TRACER
    TRACER.max_spans = 200_000  # keep long/timed-out windows untruncated
    TRACER.reset()


def _span_totals() -> dict:
    """Span name -> total ms since _trace_window()."""
    from kubernetes_tpu.utils.tracing import TRACER
    out: dict = {}
    for s in TRACER.spans():
        out[s.name] = round(out.get(s.name, 0.0) + s.duration_ms, 1)
    return out


def _serve(conn) -> None:
    """Server process: in-memory store + HTTP apiserver until told to stop."""
    from kubernetes_tpu.store.apiserver import APIServer
    server = APIServer().start()
    conn.send(server.port)
    conn.recv()  # any message = stop
    server.stop()


def check_slo_gates(result: dict, gates: dict) -> list[str]:
    """HARD SLO verdicts for a bench case: throughput floors and latency
    ceilings from the case config. A MISSING or unparseable figure fails
    exactly like a regressed one — BENCH_r05's summary crash silently
    nulled every number for three rounds, and a gate that treats None as
    'no data, pass' would do it again. Returns failure strings (empty =
    all gates green)."""
    failures: list[str] = []
    for key, bound in (gates or {}).items():
        if key == "SchedulingThroughput":
            val, ok = result.get("SchedulingThroughput"), "floor"
        elif key == "p99AttemptLatencySeconds":
            val, ok = result.get("p99_attempt_latency_s"), "ceiling"
        else:
            failures.append(f"unknown SLO gate {key!r} (refusing to skip)")
            continue
        if not isinstance(val, (int, float)):
            failures.append(f"{key}: value missing/unparsed ({val!r}) — "
                            f"gate {bound} cannot pass silently")
        elif ok == "floor" and val < bound:
            failures.append(f"{key}: {val} below the {bound} floor")
        elif ok == "ceiling" and val > bound:
            failures.append(f"{key}: {val} above the {bound}s ceiling")
    return failures


def _bench_auditor(runner, clean_client, interval_s: float = 2.0):
    """Fail-fast invariant auditor for a bench window (replaces the
    runner's production-cadence auditor BEFORE start): tight sweeps, a
    clean ground-truth client, raise-on-violation semantics."""
    from kubernetes_tpu.audit.auditor import InvariantAuditor
    return InvariantAuditor(
        client=clean_client, cache=runner.cache,
        scheduler=runner.scheduler, interval_s=interval_s, fail_fast=True,
        pre_sweep=runner.sweep_stale_nominations,
        post_sweep=runner.publish_status,
        relists=runner._total_relists)


def _audit_close(runner) -> dict:
    """Stop the bench auditor, run two settle sweeps (confirm-2 invariants
    need consecutive observations of end-state corruption), and return the
    block every audited bench case records. Never raises: the violations
    are already counted/bundled and the caller gates on the count."""
    from kubernetes_tpu.audit.auditor import InvariantViolationError
    auditor = runner.auditor
    auditor.stop()
    for _ in range(2):
        try:
            auditor.run_once()
        except InvariantViolationError:
            pass  # recorded + bundled; the count below fails the bench
    out = {"invariant_violations": auditor.total_violations,
           "audit": auditor.status()}
    sentinel = runner.scheduler.sentinel
    if sentinel is not None:
        sentinel.drain()
        out["parity"] = sentinel.stats()
    return out


def _watch_bound(url: str, ns: str, rv0: int, n_pods: int,
                 count, done, dead, ready) -> None:
    """Watcher process: count pods whose nodeName got set (one event per
    binding); its JSON decode burns its own interpreter, not the
    scheduler's."""
    from kubernetes_tpu.client.clientset import HTTPClient
    client = HTTPClient(url, timeout=30.0)
    seen: set = set()
    try:
        w = client.pods(ns).watch(since_rv=rv0)
        ready.set()  # stream established; the clock may start
        for ev in w:
            if (ev.object or {}).get("spec", {}).get("nodeName"):
                seen.add(ev.object["metadata"]["name"])
                count.value = len(seen)
                if len(seen) >= n_pods:
                    done.set()
                    return
    except Exception:
        import traceback
        traceback.print_exc()
    dead.set()


def _churn_loop(client, stop, period_s: float = 0.1, counter=None,
                hurry=None) -> None:
    """scheduler_perf's ``churn`` op analog: recycle nodes and short-lived
    pods (namespace ``churn``, excluded from the measured set) during the
    measured window. Exercises event-driven requeue
    (MoveAllToActiveOrBackoffQueue on node events), cache delta deletes,
    and the drain context's invalidate-and-rebuild path under load.
    ``hurry``: optional Event — once set, the loop drops to a 10ms cadence
    so a fixed op budget completes quickly after the measured drain."""
    import itertools
    from kubernetes_tpu.testing.wrappers import make_node, make_pod
    seq = itertools.count()
    live_nodes: list = []
    live_pods: list = []
    while not stop.is_set():
        i = next(seq)
        try:
            node = make_node(f"churn-n{i}").capacity(
                {"cpu": "2", "memory": "4Gi", "pods": "8"}).obj()
            client.nodes().create(node.to_dict())
            live_nodes.append(node.metadata.name)
            pod = make_pod(f"churn-p{i}", "churn").req({"cpu": "100m"}).obj()
            client.pods("churn").create(pod.to_dict())
            live_pods.append(pod.metadata.name)
            if len(live_nodes) > 3:
                client.nodes().delete(live_nodes.pop(0))
            if len(live_pods) > 3:
                client.pods("churn").delete(live_pods.pop(0))
            if counter is not None:
                counter["ops"] = counter.get("ops", 0) + 4
        except Exception:
            pass  # churn is background noise; the bench owns correctness
        stop.wait(period_s if hurry is None or not hurry.is_set()
                  else min(period_s, 0.01))


def run_connected(n_pods: int = 2000, n_nodes: int = 1000,
                  batch_size: int = 512, drain_batches: int = 2,
                  timeout: float = 300.0, churn: bool = False,
                  churn_period_s: float = 0.1, min_churn_ops: int = 500,
                  pipeline_depth: int | None = None,
                  chaos_seed: int | None = None,
                  explain: bool = True,
                  trace_tag: str | None = None,
                  log=lambda *a: None) -> dict:
    from kubernetes_tpu.client.clientset import HTTPClient
    from kubernetes_tpu.config.types import SchedulerConfiguration
    from kubernetes_tpu.metrics.registry import ATTEMPT_DURATION
    from kubernetes_tpu.sched.runner import SchedulerRunner
    from kubernetes_tpu.utils.tracing import FLIGHT
    from benchmarks.workloads import mixed_heterogeneous

    # explain=False is the A/B's baseline leg: explainer off AND flight
    # recorder off (run_explain_ab gates the on-leg's throughput cost)
    flight_was = FLIGHT.enabled
    FLIGHT.enabled = explain

    ctx = mp.get_context("spawn")  # never fork a live TPU client
    parent, child = ctx.Pipe()
    server = ctx.Process(target=_serve, args=(child,), daemon=True)
    server.start()
    port = parent.recv()
    url = f"http://127.0.0.1:{port}"
    schedule = device_chaos = None
    try:
        seed_client = HTTPClient(url, timeout=120.0)
        nodes, pods = mixed_heterogeneous(pods=n_pods, nodes=n_nodes)
        t0 = time.time()
        seed_client.nodes().create_many([n.to_dict() for n in nodes])
        log(f"  seeded {n_nodes} nodes in {time.time()-t0:.1f}s")

        cfg_kw = dict(batch_size=batch_size,
                      max_drain_batches=drain_batches,
                      explainer_enabled=explain)
        if pipeline_depth is not None:
            # clamp like the scheduler does, so the reported depth is the
            # depth that actually ran (depth 0 would silently run as 1)
            cfg_kw["pipeline_depth"] = max(1, int(pipeline_depth))
        sched_client = HTTPClient(url)
        if chaos_seed is not None:
            # ChaosChurn: the SCHEDULER's transport is chaos-wrapped (the
            # harness's own seed/verify clients stay clean — the bench
            # owns ground truth), device + thread faults install after
            # warmup so the measured window eats them, and the breaker
            # cooldown shrinks so half-open recovery happens inside the
            # window. The seed is logged: any failure replays from it.
            from kubernetes_tpu.chaos import ChaosClient, FaultSchedule
            schedule = FaultSchedule.generate(chaos_seed, profile="churn")
            log(f"  chaos schedule armed (seed {chaos_seed}; "
                f"KTPU_CHAOS_SEED replays it)")
            sched_client = ChaosClient(sched_client, schedule)
            cfg_kw["breaker_cooldown_s"] = 5.0
        if chaos_seed is not None:
            # chaos runs sample the parity sentinel densely: the device
            # fault burst is exactly when a wrong-answer regression would
            # hide behind the breaker's exception-only view
            cfg_kw.setdefault("parity_sample_every", 4)
        runner = SchedulerRunner(sched_client,
                                 SchedulerConfiguration(**cfg_kw))
        # fail-fast invariant audit over the whole measured run: sweeps
        # ride a CLEAN client (the bench owns ground truth; the chaos
        # wrapper stays on the scheduler's transport only) and any
        # confirmed violation is recorded + repro-bundled, then reported
        # as invariant_violations in this case's JSON — bench.py exits
        # non-zero on it (the loud-failure lesson, applied to correctness)
        runner.auditor = _bench_auditor(runner, HTTPClient(url))
        # informers first (nodes sync into the scheduler cache); the loop
        # starts after pod creation so the first pop drains a deep backlog
        runner.start(start_loop=False)
        ctx_armed = _warm_jit(runner, pods, batch_size, n_pods, log)
        chaos_base: dict = {}
        if schedule is not None:
            from kubernetes_tpu.chaos import (DeviceChaos, ThreadChaos,
                                              hooks)
            from kubernetes_tpu.metrics.registry import (BIND_RETRIES,
                                                         LOOP_ERRORS)
            device_chaos = DeviceChaos(schedule).install()
            hooks.install(ThreadChaos(schedule))
            # the registry is process-global and earlier bench phases ran
            # in this process: snapshot now, diff at report time, so the
            # chaos JSON attributes only THIS window's errors/retries
            chaos_base = {"bind_retries": BIND_RETRIES.get(),
                          "loop_errors": LOOP_ERRORS.items()}

        _, rv0 = seed_client.pods("default").list_rv()
        count = ctx.Value("i", 0)
        all_bound, watch_dead, ready = ctx.Event(), ctx.Event(), ctx.Event()
        watcher = ctx.Process(target=_watch_bound,
                              args=(url, "default", rv0, n_pods,
                                    count, all_bound, watch_dead, ready),
                              daemon=True)
        watcher.start()
        ready.wait(30.0)  # spawn + import + stream setup is seconds

        churn_stop = churn_hurry = None
        churn_stats: dict = {}
        if churn:
            import threading
            churn_stop = threading.Event()
            churn_hurry = threading.Event()
            threading.Thread(target=_churn_loop,
                             args=(HTTPClient(url), churn_stop),
                             kwargs={"counter": churn_stats,
                                     "period_s": churn_period_s,
                                     "hurry": churn_hurry},
                             daemon=True).start()

        _trace_window()  # spans from here on belong to the measured window
        # the registry is process-global: an earlier bench phase's attempts
        # (e.g. the churn workload) must not pollute this window's p99
        ATTEMPT_DURATION.reset()
        from kubernetes_tpu.metrics.registry import (E2E_SCHEDULING,
                                                     UNSCHEDULABLE_REASONS)
        FLIGHT.reset()
        E2E_SCHEDULING.reset()
        reasons_base = UNSCHEDULABLE_REASONS.items()
        t_start = time.time()
        by_ns: dict = {}
        for p in pods:
            by_ns.setdefault(p.metadata.namespace, []).append(p.to_dict())
        # concurrent bulk creates (upstream scheduler_perf's createPods op
        # runs with client-side concurrency): chunks land on separate
        # apiserver handler threads, overlapping decode/store work
        from concurrent.futures import ThreadPoolExecutor
        CREATE_CHUNK = 2500
        jobs = [(ns, objs[i:i + CREATE_CHUNK])
                for ns, objs in by_ns.items()
                for i in range(0, len(objs), CREATE_CHUNK)]

        def create(job):
            ns, objs = job
            # seed_client is thread-safe: connections live in
            # threading.local, so each pool thread gets its own socket
            seed_client.pods(ns).create_many(objs)
        with ThreadPoolExecutor(max_workers=min(4, len(jobs))) as pool:
            list(pool.map(create, jobs))
        t_created = time.time()
        runner.start_loop()
        deadline = t_start + timeout
        completed = False
        milestones: dict = {}  # fraction bound -> seconds since t_start
        while time.time() < deadline:
            n = count.value
            for frac in (0.25, 0.5, 0.75):
                if n >= n_pods * frac and frac not in milestones:
                    milestones[frac] = round(time.time() - t_start, 2)
            if all_bound.wait(timeout=0.02):
                completed = True
                break
            if watch_dead.is_set():
                # watch failed: poll the store for the truth instead of
                # silently waiting out the timeout with a dead detector
                n = sum(1 for p in seed_client.pods("default").list()
                        if p["spec"].get("nodeName"))
                count.value = n
                if n >= n_pods:
                    completed = True
                    break
                time.sleep(0.2)
        dt = time.time() - t_start
        bound = count.value
        if not completed:  # timed out: relist for the truth
            bound = sum(1 for p in seed_client.pods("default").list()
                        if p["spec"].get("nodeName"))
        # fractions crossed inside the final wait (or a sub-interval run)
        for frac in (0.25, 0.5, 0.75):
            if bound >= n_pods * frac and frac not in milestones:
                milestones[frac] = round(dt, 2)
        log(f"  created {n_pods} pods in {t_created-t_start:.1f}s; "
            f"all bound at +{dt:.1f}s")
        # Snapshot the MEASURED window's metrics BEFORE the churn budget
        # phase below: the hurry-phase keeps the live scheduler processing
        # small fast churn batches, which would otherwise skew the reported
        # p99/p50/span totals the same way an earlier phase would.
        # p99 attempt latency (scheduled results) from the live histogram —
        # bucket upper bound, like Prometheus histogram_quantile
        p99 = ATTEMPT_DURATION.percentile(0.99, {"result": "scheduled"})
        p50 = ATTEMPT_DURATION.percentile(0.50, {"result": "scheduled"})
        # where the window went: scheduler-side span totals (ms) + the bind
        # progress curve, so a BENCH file diagnoses its own bottleneck
        span_ms = _span_totals()
        attempt_buckets = [
            (b, c) for b, c in ATTEMPT_DURATION.bucket_counts(
                {"result": "scheduled"}) if c]
        ctx_stats = dict(runner.scheduler.ctx_stats)
        encode_cache = runner.cache.encode_cache_stats()
        # decision-provenance + flight-recorder attribution for this
        # window: reason breakdown, explainer thread totals (its spans are
        # explain/* in span_ms — all off the drain cycle), per-pod
        # timeline coverage, and the derived end-to-end SLI
        explain_block = None
        ex = runner.scheduler.explainer
        if ex is not None:
            ex.drain(5.0)
            explain_block = ex.stats()
            # re-snapshot AFTER the drain: a capture still queued at the
            # span_ms snapshot finishes its explain/* spans inside the
            # drain, and the cost attribution must include them
            explain_block["span_ms"] = {
                k: v for k, v in _span_totals().items()
                if k.startswith("explain/")}
        unsched_reasons = {}
        for key, v in UNSCHEDULABLE_REASONS.items().items():
            dv = v - reasons_base.get(key, 0.0)
            if dv:
                unsched_reasons["".join(k for _, k in key)] = dv
        flight_block = FLIGHT.stats()
        e2e_block = {"count": E2E_SCHEDULING.count(),
                     "p50_s": E2E_SCHEDULING.percentile(0.50),
                     "p99_s": E2E_SCHEDULING.percentile(0.99)}
        # Perfetto-loadable dump of the measured window (batch spans +
        # per-pod flight tracks): BENCH_TRACE_PATH (bench.py defaults it
        # next to the result JSON; empty string disables). The path is
        # suffixed per CASE — several cases run run_connected in one bench
        # process, and the last one must not silently overwrite the
        # headline window's trace.
        import os as _os
        case_name = ("ChaosChurn" if chaos_seed is not None
                     else "ConnectedChurn" if churn
                     else "ConnectedScheduler")
        trace_file = _os.environ.get("BENCH_TRACE_PATH") or None
        if trace_file:
            from kubernetes_tpu.utils.tracing import TRACER
            tag = trace_tag or case_name
            root, dot, ext = trace_file.rpartition(".")
            trace_file = (f"{root}.{tag}.{ext}" if dot
                          else f"{trace_file}.{tag}")
            try:
                TRACER.export_chrome(trace_file)
                log(f"  perfetto trace -> {trace_file}")
            except Exception:
                trace_file = None
        if churn_stop is not None:
            # fixed churn-op budget DECOUPLED from drain duration: a fast
            # drain must not mean the churn path went unexercised (r05: the
            # 2k-pod window shrank to 1.2s and applied only 36 ops). Keep
            # churning at a hurried cadence against the LIVE scheduler
            # until the budget lands, then tear down.
            churn_hurry.set()
            budget_deadline = time.time() + 60.0
            while (churn_stats.get("ops", 0) < min_churn_ops
                   and time.time() < budget_deadline):
                time.sleep(0.05)
            churn_stop.set()
        if schedule is not None:
            from kubernetes_tpu.chaos import hooks
            hooks.uninstall()
            if device_chaos is not None:
                device_chaos.uninstall()
                device_chaos = None
        audit_block = _audit_close(runner)
        runner.stop()
        out = {
            "case": case_name,
            "workload": f"{n_pods}x{n_nodes}",
            "SchedulingThroughput": round(bound / dt, 1) if dt > 0 else 0.0,
            "bound": bound, "pods": n_pods, "nodes": n_nodes,
            "measure_s": round(dt, 2),
            "watch_degraded": watch_dead.is_set(),
            "p99_attempt_latency_s": p99,
            "p50_attempt_latency_s": p50,
            "create_s": round(t_created - t_start, 2),
            "bound_frac_s": milestones,
            "span_ms": span_ms,
            # False = the device-resident drain context wasn't armed; the
            # window then includes compilation / fresh staging
            "jit_warmed": ctx_armed,
        }
        if churn:
            out["churn_api_ops"] = churn_stats.get("ops", 0)
        if schedule is not None:
            from kubernetes_tpu.metrics.registry import (BIND_RETRIES,
                                                         LOOP_ERRORS)
            base_errs = chaos_base.get("loop_errors", {})
            window_errs = {}
            for key, v in LOOP_ERRORS.items().items():
                dv = v - base_errs.get(key, 0.0)
                if dv:
                    window_errs["".join(k for _, k in key)] = dv
            # the gate's inputs: lost = pods the run failed to bind (the
            # caller exits non-zero on any), recovery spans per fault
            # class, and the same resilience aggregation ktpu status shows
            out["chaos"] = {
                "seed": schedule.seed,
                "lost": n_pods - bound,
                "recovery": schedule.report(),
                "resilience": runner._resilience_status(),
                "bind_retries": BIND_RETRIES.get()
                - chaos_base.get("bind_retries", 0.0),
                "loop_errors": window_errs,
            }
        # pipeline + incremental-encode attribution (measured-window
        # snapshot, like p99/spans): depth knob in effect, and how many pod
        # rows the hot path served from the informer-time compile cache
        out["ctx_stats"] = ctx_stats
        out["pipeline_depth"] = runner.cfg.pipeline_depth
        out["encode_cache"] = encode_cache
        out["attempt_buckets"] = attempt_buckets
        out["unschedulable_reasons"] = unsched_reasons
        out["explain"] = explain_block
        out["flight"] = flight_block
        out["e2e"] = e2e_block
        out["trace_file"] = trace_file
        out.update(audit_block)
        return out
    finally:
        from kubernetes_tpu.utils.tracing import FLIGHT as _FL
        _FL.enabled = flight_was
        if schedule is not None:  # crash path: never leak installed chaos
            from kubernetes_tpu.chaos import hooks as _hooks
            _hooks.uninstall()
            if device_chaos is not None:
                device_chaos.uninstall()
        try:
            parent.send("stop")
        except Exception:
            pass
        server.join(timeout=5.0)
        if server.is_alive():
            server.terminate()


def run_chaos_churn(n_pods: int = 2000, n_nodes: int = 1000,
                    batch_size: int = 512, drain_batches: int = 2,
                    timeout: float = 300.0, seed: int | None = None,
                    log=lambda *a: None) -> dict:
    """ChaosChurn: the standard churn workload under the default fault
    schedule — API error/conflict/latency storms on the scheduler's
    transport, truncated watch streams + forced relists, a device-failure
    burst that trips the circuit breaker (and must half-open back), and
    thread stalls. The gate is absolute: 100% of pods must still bind;
    ``chaos.lost`` > 0 fails the bench run (bench.py exits non-zero).
    Recovery spans per fault class land in the result JSON."""
    from kubernetes_tpu.chaos import seed_from_env
    if seed is None:
        seed = seed_from_env(0)
    return run_connected(n_pods=n_pods, n_nodes=n_nodes,
                         batch_size=batch_size,
                         drain_batches=drain_batches, timeout=timeout,
                         churn=True, chaos_seed=seed, log=log)


def run_explain_ab(n_pods: int = 2000, n_nodes: int = 1000,
                   batch_size: int = 512, drain_batches: int = 2,
                   timeout: float = 300.0, min_ratio: float = 0.95,
                   log=lambda *a: None) -> dict:
    """ExplainAB: the ConnectedChurn workload with the decision-provenance
    explainer + flight recorder ON vs OFF. The observability layer's whole
    contract is "off the hot path": the on-leg must sustain at least
    ``min_ratio`` of the off-leg's throughput (default 95% — the <=5% cost
    budget), gated HARD like PR 8's sloGates (a missing number fails)."""
    import os
    legs = {}
    # a leaked KTPU_EXPLAIN would override BOTH legs' explainer_enabled
    # config (scheduler construction reads it last), silently turning the
    # A/B into on-vs-on or off-vs-off — the gate would then price nothing
    env_explain = os.environ.pop("KTPU_EXPLAIN", None)
    try:
        for name, on in (("off", False), ("on", True)):
            log(f"  explain A/B leg: {name} ...")
            legs[name] = run_connected(
                n_pods=n_pods, n_nodes=n_nodes, batch_size=batch_size,
                drain_batches=drain_batches, timeout=timeout, churn=True,
                explain=on, trace_tag=f"ExplainAB.{name}", log=log)
    finally:
        if env_explain is not None:
            os.environ["KTPU_EXPLAIN"] = env_explain
    on_t = legs["on"].get("SchedulingThroughput")
    off_t = legs["off"].get("SchedulingThroughput")
    ratio = (round(on_t / off_t, 3)
             if isinstance(on_t, (int, float))
             and isinstance(off_t, (int, float)) and off_t else None)
    failures = []
    if ratio is None:
        failures.append(
            f"throughput ratio unavailable (on={on_t!r}, off={off_t!r}) — "
            "the <=5% overhead gate cannot pass silently")
    elif ratio < min_ratio:
        failures.append(
            f"explainer+flight overhead too high: on/off throughput "
            f"ratio {ratio} below the {min_ratio} floor")
    # the A/B must actually have measured on-vs-off: the on leg carries
    # the layer it is pricing, the off leg provably does not
    ex = (legs["on"].get("explain") or {})
    if legs["on"].get("explain") is None:
        failures.append("on-leg ran without the explainer constructed")
    if legs["off"].get("explain") is not None:
        failures.append("off-leg ran WITH the explainer (A/B invalid)")
    if not (legs["on"].get("flight") or {}).get("enabled"):
        failures.append("on-leg ran with the flight recorder disabled")
    out = {
        "case": "ExplainAB",
        "workload": f"{n_pods}x{n_nodes}churn",
        "throughput_on": on_t, "throughput_off": off_t,
        "throughput_ratio": ratio, "min_ratio": min_ratio,
        "explain_on": ex,
        "unschedulable_reasons": legs["on"].get("unschedulable_reasons"),
        "e2e_on": legs["on"].get("e2e"),
        "slo_failures": failures,
        "invariant_violations": sum(
            int(leg.get("invariant_violations") or 0)
            for leg in legs.values()),
        "legs": {name: {k: leg.get(k) for k in
                        ("SchedulingThroughput", "bound", "measure_s",
                         "p99_attempt_latency_s", "jit_warmed")}
                 for name, leg in legs.items()},
    }
    return out


def drain_parity_check(mesh_shape: tuple[int, int], n_nodes: int = 1024,
                       P: int = 128, B: int = 2) -> dict:
    """Deterministic mesh acceptance gate: the FULL fused drain over the
    bench workload, sharded vs unsharded, must produce bit-identical
    placements and fold arithmetic (same check as __graft_entry__'s
    multichip dry-run, at the live path's shapes). bench.py exits non-zero
    when this reports ok=False."""
    import jax
    import numpy as np
    from benchmarks.workloads import mixed_heterogeneous
    from kubernetes_tpu.encode.snapshot import SnapshotEncoder
    from kubernetes_tpu.models.gang import (drain_step, extend_cluster_drain,
                                            unify_batches)
    from kubernetes_tpu.parallel.mesh import mesh_from_shape, shard_drain

    n_pods = P * B
    nodes, pods = mixed_heterogeneous(pods=n_pods, nodes=n_nodes)
    enc = SnapshotEncoder()
    ct, meta = enc.encode_cluster(nodes, [], pending_pods=pods)
    chunks = [pods[i:i + P] for i in range(0, n_pods, P)]
    pbs = unify_batches([enc.encode_pods(c, meta, min_p=P) for c in chunks])
    ct_all, e0 = extend_cluster_drain(ct, pbs)
    pb_stack = jax.tree_util.tree_map(lambda *xs: np.stack(xs), *pbs)
    kw = dict(e0=e0, seed=0, fit_strategy="LeastAllocated",
              topo_keys=meta.topo_keys, weights=(), enabled_filters=(),
              max_rounds=64)
    a_u, _, _, fill_u = drain_step(ct_all, pb_stack, 0, **kw)
    a_u, fill_u = jax.device_get((a_u, fill_u))
    mesh = mesh_from_shape(mesh_shape)
    ct_all2, _ = extend_cluster_drain(ct, pbs)
    with mesh:
        # mesh= pins the output shardings to the input shardings (the
        # donate-through contract) — the exact program the live leg runs
        ct_s, pb_s = shard_drain(mesh, ct_all2, pb_stack)
        a_s, _, _, fill_s = drain_step(ct_s, pb_s, 0, mesh=mesh, **kw)
        a_s, fill_s = jax.device_get((a_s, fill_s))
    a_u, a_s = np.asarray(a_u), np.asarray(a_s)
    mism = int((a_u != a_s).sum())
    return {"ok": bool(mism == 0 and int(fill_u) == int(fill_s)
                       and int(fill_u) > 0),
            "mismatches": mism, "placed": int(fill_u),
            "pods": n_pods, "nodes": n_nodes,
            "mesh": f"{mesh_shape[0]}x{mesh_shape[1]}"}


def _run_mesh_leg(mesh_shape, n_pods: int, n_nodes: int, batch_size: int,
                  drain_batches: int, timeout: float, log) -> dict:
    """One live leg of the ConnectedMesh case: separate-process apiserver,
    a HOLLOW-KUBELET node fleet (kubemark nodes registering + syncing pods
    over HTTP), and the connected scheduler — mesh on or off per
    ``mesh_shape``. Measured window matches run_connected: pod creation to
    last binding visible."""
    from kubernetes_tpu.client.clientset import HTTPClient
    from kubernetes_tpu.config.types import SchedulerConfiguration
    from kubernetes_tpu.kubelet.kubemark import HollowCluster
    from kubernetes_tpu.sched.runner import SchedulerRunner
    from kubernetes_tpu.testing.wrappers import make_pod

    ctx = mp.get_context("spawn")
    parent, child = ctx.Pipe()
    server = ctx.Process(target=_serve, args=(child,), daemon=True)
    server.start()
    port = parent.recv()
    url = f"http://127.0.0.1:{port}"
    cluster = None
    runner = None
    try:
        seed_client = HTTPClient(url, timeout=120.0)
        t0 = time.time()
        cluster = HollowCluster(HTTPClient(url, timeout=60.0), n_nodes,
                                heartbeat_period=30.0).start()
        log(f"  {n_nodes} hollow kubelets up in {time.time()-t0:.1f}s")
        pods = [make_pod(f"mp{i:05d}", "default")
                .req({"cpu": "500m", "memory": "256Mi"}).obj()
                for i in range(n_pods)]
        runner = SchedulerRunner(
            HTTPClient(url),
            SchedulerConfiguration(batch_size=batch_size,
                                   max_drain_batches=drain_batches,
                                   mesh_shape=mesh_shape))
        # churn legs run under fail-fast audit too: a sharded program that
        # silently corrupts placements must fail THIS leg, not surface as
        # a throughput anomaly three rounds later
        runner.auditor = _bench_auditor(runner, HTTPClient(url))
        runner.start(wait_sync=30.0, start_loop=False)
        armed = _warm_jit(runner, pods, batch_size, n_pods, log)
        mesh = runner.scheduler._mesh

        _, rv0 = seed_client.pods("default").list_rv()
        count = ctx.Value("i", 0)
        all_bound, watch_dead, ready = ctx.Event(), ctx.Event(), ctx.Event()
        watcher = ctx.Process(target=_watch_bound,
                              args=(url, "default", rv0, n_pods,
                                    count, all_bound, watch_dead, ready),
                              daemon=True)
        watcher.start()
        ready.wait(30.0)

        _trace_window()
        from kubernetes_tpu.metrics.registry import ATTEMPT_DURATION
        ATTEMPT_DURATION.reset()
        t_start = time.time()
        objs = [p.to_dict() for p in pods]
        CHUNK = 2500
        for i in range(0, len(objs), CHUNK):
            seed_client.pods("default").create_many(objs[i:i + CHUNK])
        runner.start_loop()
        deadline = t_start + timeout
        completed = False
        while time.time() < deadline:
            if all_bound.wait(timeout=0.05):
                completed = True
                break
            if watch_dead.is_set():
                n = sum(1 for p in seed_client.pods("default").list()
                        if p["spec"].get("nodeName"))
                count.value = n
                if n >= n_pods:
                    completed = True
                    break
                time.sleep(0.2)
        dt = time.time() - t_start
        bound = count.value
        if not completed:
            bound = sum(1 for p in seed_client.pods("default").list()
                        if p["spec"].get("nodeName"))
        p99 = ATTEMPT_DURATION.percentile(0.99, {"result": "scheduled"})
        span_ms = _span_totals()
        encode_cache = runner.cache.encode_cache_stats()
        staging = runner.cache.staging_stats()
        from kubernetes_tpu.metrics.registry import RESOLVE_BYTES
        audit_block = _audit_close(runner)
        log(f"  mesh={mesh_shape}: {bound}/{n_pods} bound at +{dt:.1f}s")
        return {
            "mesh": (f"{mesh_shape[0]}x{mesh_shape[1]}"
                     if mesh_shape else "off"),
            "mesh_active": mesh is not None,
            "SchedulingThroughput": round(bound / dt, 1) if dt > 0 else 0.0,
            "bound": bound, "pods": n_pods, "hollow_nodes": n_nodes,
            "measure_s": round(dt, 2),
            "p99_attempt_latency_s": p99,
            "span_ms": span_ms,
            # zero-copy attribution (the r06 lesson: a transfer hiding in
            # a dispatch span cost two rounds): staging spans broken out,
            # the h2d swap/fallback split, and the winners-fetch bytes
            "stage_batch_ms": span_ms.get("scheduler/stage_batch", 0.0),
            "stage_swap_ms": span_ms.get("scheduler/stage_swap", 0.0),
            "staging": staging,
            "resolve_bytes": RESOLVE_BYTES.get(),
            "encode_cache": encode_cache,
            "jit_warmed": armed,
            **audit_block,
        }
    finally:
        try:
            if runner is not None:
                runner.stop()
        except Exception:
            pass
        try:
            if cluster is not None:
                cluster.stop()
        except Exception:
            pass
        try:
            parent.send("stop")
        except Exception:
            pass
        server.join(timeout=5.0)
        if server.is_alive():
            server.terminate()


def run_connected_mesh(mesh_shapes=((1, 2),),
                       n_pods: int = 1024, n_nodes: int = 96,
                       batch_size: int = 128, drain_batches: int = 2,
                       timeout: float = 300.0, slo_gates: dict | None = None,
                       min_ratio: float = 1.0,
                       log=lambda *a: None, mesh_shape=None) -> dict:
    """ConnectedMesh case: a WIDTH SWEEP. One unsharded live leg (the
    baseline), then per mesh width: the deterministic sharded-vs-unsharded
    drain parity gate and a sharded live leg, with per-leg
    stage_batch/stage_swap spans, resolve_bytes, and staging-arena health.

    HARD gate per width: sharded throughput >= ``min_ratio`` x unsharded
    (SLO-style — a MISSING ratio fails exactly like a regressed one; the
    zero-copy steady state exists to make the sharded leg strictly
    dominate). A width whose parity check or leg CRASHES is environmental
    (virtual-CPU GSPMD miscompiles some widths on this jaxlib): recorded,
    excluded from the ratio gate, and excluded from the parity verdict —
    only a genuine ok=False divergence fails the bench.

    Needs a backend with >= max(pods*nodes) mesh devices — bench.py
    launches this in a subprocess with a forced multi-device CPU host
    platform, since the benchmark box exposes one real TPU chip.
    ``mesh_shape`` (single tuple) is accepted for back-compat callers."""
    import jax
    if mesh_shape is not None:
        mesh_shapes = (mesh_shape,)
    mesh_shapes = [tuple(s) for s in mesh_shapes]
    out = {"case": "ConnectedMesh",
           "workload": f"{n_pods}x{n_nodes}hollow",
           "widths": {}}
    if slo_gates is None:
        slo_gates = {"SchedulingThroughput": 60,
                     "p99AttemptLatencySeconds": 10}
    out["slo_gates"] = dict(slo_gates, shardedVsUnshardedRatio=min_ratio)
    runnable = [s for s in mesh_shapes
                if s[0] * s[1] <= jax.device_count()]
    for s in mesh_shapes:
        if s not in runnable:
            out["widths"][f"{s[0]}x{s[1]}"] = {
                "skipped": True,
                "reason": (f"needs {s[0] * s[1]} devices, have "
                           f"{jax.device_count()}")}
    if not runnable:
        out.update(skipped=True, invariant_violations=0,
                   reason="no runnable mesh width on this backend")
        return out

    slo_failures: list[str] = []
    log("  live leg: unsharded baseline ...")
    try:
        unsharded = _run_mesh_leg(None, n_pods, n_nodes, batch_size,
                                  drain_batches, timeout, log)
    except Exception as e:
        unsharded = {"error": f"{type(e).__name__}: {e}"[:300],
                     "mesh": "off"}
        log(f"  unsharded leg crashed: {type(e).__name__}")
    out["unsharded"] = unsharded
    un_tput = unsharded.get("SchedulingThroughput")
    if "error" in unsharded:
        # the baseline is SINGLE-DEVICE — no GSPMD environmental excuse
        # applies, and without it every width's ratio gate is blind:
        # that is a bench failure, not a skip (missing number = failure)
        slo_failures.append(
            "unsharded baseline leg crashed "
            f"({unsharded['error']}); ratio gates cannot run")
    else:
        slo_failures += [f"unsharded: {m}"
                         for m in check_slo_gates(unsharded, slo_gates)]

    parity_verdicts: dict[str, bool] = {}
    for shape in runnable:
        name = f"{shape[0]}x{shape[1]}"
        w: dict = {}
        out["widths"][name] = w
        log(f"  parity gate (drain sharded {name} vs unsharded) ...")
        try:
            w["parity"] = drain_parity_check(shape, P=batch_size,
                                             B=drain_batches)
            parity_verdicts[name] = bool(w["parity"]["ok"])
            log("  parity: " + str(w["parity"]))
        except Exception as e:
            # the sharded program CRASHED at this width — the PR-5
            # environmental-miscompile contract: record, skip the leg,
            # no parity verdict (only a real divergence may fail)
            w["parity"] = {"ok": None,
                           "error": f"{type(e).__name__}: {e}"[:300]}
            log(f"  parity check crashed at {name}: {type(e).__name__}")
            continue
        if not w["parity"]["ok"]:
            continue  # live leg would measure a miscompiling backend
        log(f"  live leg: sharded {name} ...")
        try:
            leg = _run_mesh_leg(shape, n_pods, n_nodes, batch_size,
                                drain_batches, timeout, log)
        except Exception as e:
            w["sharded"] = {"error": f"{type(e).__name__}: {e}"[:300],
                            "mesh": name}
            log(f"  sharded leg {name} crashed: {type(e).__name__}")
            continue
        w["sharded"] = leg
        sh_tput = leg.get("SchedulingThroughput")
        ratio = (round(sh_tput / un_tput, 3)
                 if un_tput and sh_tput else None)
        w["throughput_ratio"] = ratio
        w["all_bound"] = (unsharded.get("bound") == n_pods
                          and leg.get("bound") == n_pods)
        slo_failures += [f"sharded {name}: {m}"
                         for m in check_slo_gates(leg, slo_gates)]
        # the zero-copy gate: sharded must dominate at EVERY width that
        # ran; a missing ratio (either leg lost its number) fails too
        if "error" not in unsharded and (ratio is None
                                         or ratio < min_ratio):
            slo_failures.append(
                f"{name}: sharded/unsharded throughput ratio "
                f"{ratio} < {min_ratio} (missing = failure)")

    # aggregate parity verdict over widths that produced one (bench.py
    # exits non-zero on ok=False: divergence is never perf variance)
    out["parity"] = {"ok": (all(parity_verdicts.values())
                            if parity_verdicts else None),
                    "widths": parity_verdicts}
    # back-compat convenience: first width's figures at the top level
    first = next((out["widths"][f"{s[0]}x{s[1]}"] for s in runnable
                  if "sharded" in out["widths"][f"{s[0]}x{s[1]}"]), None)
    if first is not None:
        out["sharded"] = first["sharded"]
        out["throughput_ratio"] = first.get("throughput_ratio")
        out["all_bound"] = first.get("all_bound")
    out["slo_failures"] = slo_failures
    # summary-level audit figure: a MULTICHIP JSON without it is refused
    # by bench.py (the loud-failure lesson — a missing field must never
    # read as "zero violations")
    out["invariant_violations"] = (
        int(unsharded.get("invariant_violations") or 0)
        + sum(int((w.get("sharded") or {}).get("invariant_violations")
                  or 0) for w in out["widths"].values()))
    return out


def run_connected_preemption(n_nodes: int = 5000, n_high: int = 128,
                             pods_per_node: int = 2, timeout: float = 300.0,
                             log=lambda *a: None) -> dict:
    """Mixed schedule+preempt through the PRODUCT: a saturated cluster
    behind the live apiserver, a wave of high-priority pods arrives, and
    the connected scheduler's failure path must wave-preempt (evict via the
    API), nominate, and re-bind — measured pod-creation to last binding
    visible, like the plain connected run. Exercises
    scheduler._handle_failures -> _default_preempt_wave -> runner._evict
    end to end (VERDICT r3: preemption had never run through the product)."""
    from kubernetes_tpu.client.clientset import HTTPClient
    from kubernetes_tpu.config.types import SchedulerConfiguration
    from kubernetes_tpu.sched.runner import SchedulerRunner
    from kubernetes_tpu.testing.wrappers import make_node, make_pod

    ctx = mp.get_context("spawn")
    parent, child = ctx.Pipe()
    server = ctx.Process(target=_serve, args=(child,), daemon=True)
    server.start()
    port = parent.recv()
    url = f"http://127.0.0.1:{port}"
    try:
        seed_client = HTTPClient(url, timeout=120.0)
        t0 = time.time()
        seed_client.nodes().create_many(
            [make_node(f"n{i}").capacity(
                {"cpu": "8", "memory": "32Gi", "pods": "32"}).obj().to_dict()
             for i in range(n_nodes)])
        low = []
        for i in range(n_nodes):
            for j in range(pods_per_node):
                low.append(make_pod(f"low-{i}-{j}", "default")
                           .req({"cpu": "4", "memory": "4Gi"})
                           .priority(1 + (i + j) % 5).node(f"n{i}").obj()
                           .to_dict())
        seed_client.pods("default").create_many(low)
        log(f"  seeded {n_nodes} nodes + {len(low)} bound low-prio pods "
            f"in {time.time()-t0:.1f}s")

        runner = SchedulerRunner(
            HTTPClient(url), SchedulerConfiguration(batch_size=256,
                                                    max_drain_batches=1))
        runner.start(wait_sync=60.0, start_loop=False)
        warmed = _warm_preempt(runner, n_high, log)

        _trace_window()
        high = [make_pod(f"hi-{k}", "preempt")
                .req({"cpu": "6", "memory": "8Gi"}).priority(100).obj()
                for k in range(n_high)]
        _, rv0 = seed_client.pods("preempt").list_rv()
        count = ctx.Value("i", 0)
        all_bound, watch_dead, ready = ctx.Event(), ctx.Event(), ctx.Event()
        watcher = ctx.Process(target=_watch_bound,
                              args=(url, "preempt", rv0, n_high,
                                    count, all_bound, watch_dead, ready),
                              daemon=True)
        watcher.start()
        ready.wait(30.0)

        t_start = time.time()
        seed_client.pods("preempt").create_many([p.to_dict() for p in high])
        runner.start_loop()
        deadline = t_start + timeout
        completed = False
        while time.time() < deadline:
            if all_bound.wait(timeout=0.05):
                completed = True
                break
            if watch_dead.is_set():
                n = sum(1 for p in seed_client.pods("preempt").list()
                        if p["spec"].get("nodeName"))
                count.value = n
                if n >= n_high:
                    completed = True
                    break
                time.sleep(0.2)
        dt = time.time() - t_start
        bound = count.value
        if not completed:
            bound = sum(1 for p in seed_client.pods("preempt").list()
                        if p["spec"].get("nodeName"))
        log(f"  {bound}/{n_high} preemptors bound at +{dt:.1f}s")
        runner.stop()
        span_ms = _span_totals()
        remaining = len(seed_client.pods("default").list())
        return {
            "case": "ConnectedPreemption",
            "workload": f"{n_high}x{n_nodes}",
            "PreemptionThroughput": round(bound / dt, 1) if dt > 0 else 0.0,
            "resolved": bound, "preemptors": n_high, "nodes": n_nodes,
            "measure_s": round(dt, 2),
            "victims_evicted": len(low) - remaining,
            "watch_degraded": watch_dead.is_set(),
            "span_ms": span_ms,
            # False = compilation happened INSIDE the measured window; the
            # throughput is then not comparable run to run
            "jit_warmed": warmed,
        }
    finally:
        try:
            parent.send("stop")
        except Exception:
            pass
        server.join(timeout=5.0)
        if server.is_alive():
            server.terminate()


def _warm_preempt(runner, n_high: int, log) -> bool:
    """Compile the preemption-path device programs BEFORE the measured
    window, mutating nothing: the gang program at the failure batch's
    shapes, the [Q,N] static-mask filters, and the Q-length wave scan
    (scan length is structural, so Q must match n_high). A long-lived
    scheduler amortizes these once; the bench should measure preemption
    resolution, not XLA compilation."""
    import time as _time
    t0 = _time.time()
    from kubernetes_tpu.models.gang import gang_schedule
    from kubernetes_tpu.sched import preemption as pmod
    from kubernetes_tpu.testing.wrappers import make_pod
    cache = runner.cache
    profile = runner.cfg.profiles[0]
    warm = [make_pod(f"warm-{k}", "warmup")
            .req({"cpu": "6", "memory": "8Gi"}).priority(100).obj()
            for k in range(n_high)]
    ok = True
    try:
        from kubernetes_tpu.sched.scheduler import DRAIN_NOM_BUCKET
        nodes, ct, meta = cache.snapshot(pending_pods=warm)
        bound = cache.bound_pods()
        # the runtime group path pins batch width to cfg.batch_size and the
        # nominee overlay to DRAIN_NOM_BUCKET — compile exactly those
        # shapes, with and without reservations (first cycle has none)
        pb = cache.encode_pods(warm, meta, min_p=runner.cfg.batch_size)
        gang_schedule(ct, pb, seed=runner.cfg.seed,
                      fit_strategy=profile.fit_strategy,
                      topo_keys=meta.topo_keys, weights=profile.weights(),
                      enabled_filters=profile.enabled_filters)
        nom = [(meta.node_names[0], 100, warm[0])]
        ct_nom = cache.overlay_nominated(ct, meta, nom,
                                         min_m=DRAIN_NOM_BUCKET)
        gang_schedule(ct_nom, pb, seed=runner.cfg.seed,
                      fit_strategy=profile.fit_strategy,
                      topo_keys=meta.topo_keys, weights=profile.weights(),
                      enabled_filters=profile.enabled_filters)
        # same bucket pinning as the scheduler's wave path, so every wave
        # of the storm hits the programs compiled here
        masks = pmod.tensor_static_masks(
            nodes, warm, ct=ct, meta=meta, encode_pods=cache.encode_pods,
            min_p=pmod.WAVE_BUCKET)
        from kubernetes_tpu.ops.preemption import dry_run_wave
        dry_run_wave(nodes, bound, warm, [], static_masks=masks,
                     min_q=pmod.WAVE_BUCKET)
    except Exception:
        import traceback
        traceback.print_exc()
        ok = False
    log(f"  preempt warmup {_time.time()-t0:.1f}s (ok: {ok})")
    return ok


def _warm_jit(runner, pods, batch_size, n_pods, log):
    """Compile the fused drain and arm the device-resident cluster context
    at the exact shapes the runner's pops will use, against the runner's OWN
    cache — so the measured window is pure steady state (a long-lived
    scheduler amortizes this once per shape bucket, as in scheduler_perf)."""
    t0 = time.time()
    armed = runner.scheduler.warm_drain(
        pods, slot_headroom=n_pods
        + batch_size * runner.cfg.max_drain_batches)
    log(f"  jit warmup {time.time()-t0:.1f}s (ctx armed: {armed})")
    return armed


if __name__ == "__main__":
    import json
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    if len(sys.argv) > 1 and sys.argv[1] == "mesh":
        # ConnectedMesh entry: bench.py launches this in a subprocess with
        # JAX_PLATFORMS=cpu + --xla_force_host_platform_device_count so the
        # mesh has devices to span (the bench box has one real chip).
        # Each leg pins its own mesh via cfg.mesh_shape; a leaked KTPU_MESH
        # would override BOTH legs and corrupt the A/B
        os.environ.pop("KTPU_MESH", None)
        from kubernetes_tpu.parallel.mesh import parse_mesh_shape
        shapes_env = os.environ.get(
            "BENCH_MESH_SHAPES",
            os.environ.get("BENCH_MESH_SHAPE", "1x2"))
        # "off"/"none" tokens DISABLE (parse -> None, filtered) — same
        # no-silent-default rule as bench.py's parent-side parse
        shapes = [s for s in (parse_mesh_shape(tok) for tok in
                              shapes_env.replace(";", " ").split())
                  if s is not None]
        if not shapes:
            print(json.dumps({"case": "ConnectedMesh", "skipped": True,
                              "reason": f"no mesh widths in "
                                        f"{shapes_env!r}"}))
            sys.exit(0)
        res = run_connected_mesh(
            mesh_shapes=shapes,
            n_pods=int(os.environ.get("BENCH_MESH_PODS", "1024")),
            n_nodes=int(os.environ.get("BENCH_MESH_NODES", "96")),
            batch_size=int(os.environ.get("BENCH_MESH_BATCH", "128")),
            slo_gates={
                "SchedulingThroughput":
                    float(os.environ.get("BENCH_MESH_SLO_TPUT", "60")),
                "p99AttemptLatencySeconds":
                    float(os.environ.get("BENCH_MESH_SLO_P99", "10")),
            },
            # sharded >= unsharded is the GOAL gate (ROADMAP; export
            # BENCH_MESH_MIN_RATIO=1.0 on real multi-chip hardware). The
            # bench box is ONE physical core faking N devices — the
            # sharded program does strictly more work on the same silicon,
            # so the box-calibrated default (PR-8 SLO precedent) guards
            # regressions (a staging regression measured ~0.5) without
            # failing on physics. Observed here post-zero-copy: 0.77-0.92.
            min_ratio=float(os.environ.get("BENCH_MESH_MIN_RATIO", "0.7")),
            log=lambda *a: print(*a, file=sys.stderr))
        print(json.dumps(res))
        # exit gate: only a REAL divergence verdict fails (ok=False); a
        # sweep whose every width crashed environmentally carries ok=None
        sys.exit(1 if res.get("parity", {}).get("ok") is False else 0)
    _pipe = os.environ.get("BENCH_CONNECTED_PIPELINE")
    res = run_connected(
        n_pods=int(os.environ.get("BENCH_CONNECTED_PODS", "2000")),
        n_nodes=int(os.environ.get("BENCH_CONNECTED_NODES", "1000")),
        batch_size=int(os.environ.get("BENCH_CONNECTED_BATCH", "512")),
        drain_batches=int(os.environ.get("BENCH_CONNECTED_DRAIN", "2")),
        pipeline_depth=int(_pipe) if _pipe else None,
        log=lambda *a: print(*a, file=sys.stderr))
    print(json.dumps(res))
