"""Connected-path benchmark: SchedulerRunner against the in-process apiserver.

The raw gang numbers (scheduler_perf.py) measure the device program alone;
this measures the PRODUCT — informers watching the apiserver, the scheduling
queue, the cache's incremental snapshot encode, the gang step, and async
binding POSTs — the same window the reference's scheduler_perf measures
against a real apiserver with hollow nodes (SURVEY §4: integration tier +
kubemark).

Pods are created first (queue fills via the watch), then the scheduler loop
starts; throughput = pods bound / time from loop start to last binding
visible in the store.
"""

from __future__ import annotations

import time


def run_connected(n_pods: int = 2000, n_nodes: int = 1000,
                  batch_size: int = 512, timeout: float = 300.0,
                  log=lambda *a: None) -> dict:
    from kubernetes_tpu.client.clientset import DirectClient, HTTPClient
    from kubernetes_tpu.config.types import SchedulerConfiguration
    from kubernetes_tpu.metrics.registry import ATTEMPT_DURATION
    from kubernetes_tpu.sched.runner import SchedulerRunner
    from kubernetes_tpu.store.apiserver import APIServer
    from benchmarks.workloads import mixed_heterogeneous

    server = APIServer().start()
    try:
        seed_client = DirectClient(server.store)  # fast seeding path
        nodes, pods = mixed_heterogeneous(pods=n_pods, nodes=n_nodes)
        t0 = time.time()
        for n in nodes:
            seed_client.nodes().create(n.to_dict())
        for p in pods:
            seed_client.pods(p.metadata.namespace).create(p.to_dict())
        log(f"  seeded {n_nodes} nodes + {n_pods} pods in {time.time()-t0:.1f}s")

        runner = SchedulerRunner(
            HTTPClient(server.url),
            SchedulerConfiguration(batch_size=batch_size))
        _warm_jit(runner, nodes, pods, batch_size, log)

        # Completion detector: a watch stream counting pods whose nodeName
        # got set — one cheap event per binding instead of re-listing (and
        # deep-copying) the whole pod set in a poll loop, which at 2k+ pods
        # steals enough GIL time to distort the measurement itself.
        import threading
        bound_names: set = set()
        all_bound = threading.Event()
        _, rv0 = seed_client.pods("default").list_rv()

        def _count_bindings():
            try:
                for ev in seed_client.pods("default").watch(since_rv=rv0):
                    if (ev.object or {}).get("spec", {}).get("nodeName"):
                        bound_names.add(ev.object["metadata"]["name"])
                        if len(bound_names) >= n_pods:
                            all_bound.set()
                            return
            except Exception:
                pass  # server stopping

        watcher = threading.Thread(target=_count_bindings, daemon=True)
        watcher.start()
        t_start = time.time()
        runner.start()
        completed = all_bound.wait(timeout)
        dt = time.time() - t_start
        bound = len(bound_names)
        if not completed:  # watch died or timed out: relist for the truth
            bound = sum(1 for p in seed_client.pods("default").list()
                        if p["spec"].get("nodeName"))
        runner.stop()
        # p99 attempt latency (scheduled results) from the live histogram —
        # bucket upper bound, like Prometheus histogram_quantile
        p99 = ATTEMPT_DURATION.percentile(0.99, {"result": "scheduled"})
        return {
            "case": "ConnectedScheduler", "workload": f"{n_pods}x{n_nodes}",
            "SchedulingThroughput": round(bound / dt, 1) if dt > 0 else 0.0,
            "bound": bound, "pods": n_pods, "nodes": n_nodes,
            "measure_s": round(dt, 2),
            "p99_attempt_latency_s": p99,
        }
    finally:
        server.stop()


def _warm_jit(runner, nodes, pods, batch_size, log):
    """Compile the gang program at the exact shapes/static-args the runner's
    first batch will use (a long-lived scheduler amortizes this once per shape
    bucket; the measured window is steady-state, as in scheduler_perf)."""
    from kubernetes_tpu.models.gang import gang_schedule
    from kubernetes_tpu.sched.cache import SchedulerCache

    t0 = time.time()
    cache = SchedulerCache()
    for n in nodes:
        cache.add_node(n)
    profile = runner.cfg.profile_for(pods[0].spec.scheduler_name)
    batch = pods[:batch_size]
    _, ct, meta = cache.snapshot(pending_pods=batch, slot_headroom=len(pods))
    pb = cache.encode_pods(batch, meta)
    gang_schedule(ct, pb, seed=runner.cfg.seed,
                  fit_strategy=profile.fit_strategy,
                  topo_keys=meta.topo_keys,
                  max_rounds=runner.cfg.max_gang_rounds,
                  weights=profile.weights(),
                  enabled_filters=profile.enabled_filters)
    log(f"  jit warmup {time.time()-t0:.1f}s")


if __name__ == "__main__":
    import json
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    res = run_connected(
        n_pods=int(os.environ.get("BENCH_CONNECTED_PODS", "2000")),
        n_nodes=int(os.environ.get("BENCH_CONNECTED_NODES", "1000")),
        log=lambda *a: print(*a, file=sys.stderr))
    print(json.dumps(res))
