"""DisasterChurn: a control-plane process dies (SIGKILL) under live
churn and the whole stack survives its restart.

Two legs (``BENCH_DISASTER_CASE`` selects; default ``apiserver``):

  apiserver       the durable apiserver subprocess is killed and
                  restarted from its WAL (``run_disaster_churn``).
  scheduler-kill  the SCHEDULER subprocess is killed mid-churn and
                  restarted against the surviving apiserver
                  (``run_scheduler_kill``): with the durable AOT
                  executable cache configured, the restarted process
                  boots warm from disk — the recovery window must show
                  ZERO genuine XLA compiles (the child's compile meter
                  is the witness; a missing number is a failure), first
                  bind within seconds of loop-live, no duplicate binds,
                  no stale nominations, 0 invariant violations under a
                  fail-fast auditor running INSIDE the restarted child.

The canonical control-plane robustness scenario (upstream treats
etcd/apiserver restart + mass node-unready fallout as exactly this): a
hollow fleet heartbeats and runs pods, the scheduler binds a sustained
churn stream, the node-lifecycle controller watches for staleness — and
mid-window the apiserver subprocess is SIGKILLed, then restarted from
the SAME ``data_dir`` (WAL + snapshot replay, ``/readyz`` 503 until
done) on the SAME port. Every layer must heal through its own
discipline: HTTPClient full-jitter backoff absorbs the refused-
connection storm, informers relist (410/TooOld on pre-restart rvs),
fleet batchers back off + re-coalesce + re-assert on reconnect, and the
node-lifecycle disruption mode keeps the fleet-wide lease staleness the
outage manufactured from cascading into a taint/evict storm.

Hard gates (missing number = failure, the PR-8 SLO discipline):
  - every pod that exists at the end is BOUND (none lost, none stuck)
  - 0 confirmed invariant violations (fail-fast auditor live throughout)
  - 0 outage-caused evictions, 0 lifecycle taints left on any node —
    with the disruption mode provably ENGAGED during the outage and
    RELEASED after heal (protection that never fires protects nothing)
  - time-to-first-bind-after-restart <= ``bind_slo_s`` (default 10s)
  - the restarted server reached /readyz 200 (replay completed)
"""

from __future__ import annotations

import os
import shutil
import tempfile
import threading
import time


def _pod_churn_loop(client, stop, counter, period_s: float = 0.25) -> None:
    """Sustained pod churn (namespace ``churn``): a rolling window of
    short-lived pods. Errors are EXPECTED mid-outage (the apiserver is
    dead); the loop keeps trying and counts what committed."""
    import itertools

    from kubernetes_tpu.testing.wrappers import make_pod
    seq = itertools.count()
    live: list = []
    while not stop.is_set():
        i = next(seq)
        try:
            pod = make_pod(f"churn-p{i}", "churn").req(
                {"cpu": "100m"}).obj()
            client.pods("churn").create(pod.to_dict())
            live.append(pod.metadata.name)
            if len(live) > 4:
                client.pods("churn").delete(live.pop(0))
            counter["ops"] = counter.get("ops", 0) + 2
        except Exception:
            counter["errors"] = counter.get("errors", 0) + 1
        stop.wait(period_s)


def _unbound(client, namespaces=("default", "churn")) -> list[str]:
    out = []
    for ns in namespaces:
        for p in client.pods(ns).list():
            if not (p.get("spec") or {}).get("nodeName"):
                out.append(f"{ns}/{p['metadata']['name']}")
    return out


def _lifecycle_taints(client) -> list[str]:
    from kubernetes_tpu.controllers.nodelifecycle import (
        TAINT_NOT_READY, TAINT_UNREACHABLE)
    out = []
    for n in client.nodes().list():
        for t in (n.get("spec") or {}).get("taints") or []:
            if t.get("key") in (TAINT_NOT_READY, TAINT_UNREACHABLE):
                out.append(f"{n['metadata']['name']}:{t['key']}")
    return out


def run_disaster_churn(n_hollow: int = 48, n_pods: int = 96,
                       outage_s: float = 16.0, grace_s: float = 12.0,
                       heartbeat_period: float = 1.0,
                       bind_slo_s: float = 10.0,
                       settle_timeout: float = 120.0,
                       timeout: float = 240.0,
                       log=lambda *a: None) -> dict:
    from benchmarks.connected import _audit_close, _bench_auditor
    from kubernetes_tpu.chaos.apiserver import ApiServerProcess
    from kubernetes_tpu.client.clientset import HTTPClient
    from kubernetes_tpu.client.informer import InformerFactory
    from kubernetes_tpu.config.types import SchedulerConfiguration
    from kubernetes_tpu.controllers.nodelifecycle import (
        MODE_NORMAL, NodeLifecycleController)
    from kubernetes_tpu.kubelet.kubemark import HollowCluster
    from kubernetes_tpu.sched.runner import SchedulerRunner
    from kubernetes_tpu.testing.wrappers import make_pod

    # grace must clear the fleet's lease cadence (min(10, hb*5)) with
    # margin, or steady state itself flaps unready under suite load
    lease_period = min(10.0, heartbeat_period * 5)
    assert grace_s > 2 * lease_period, \
        f"grace {grace_s}s too tight for lease period {lease_period}s"

    data_dir = tempfile.mkdtemp(prefix="ktpu-disaster-")
    result: dict = {"case": "DisasterChurn",
                    "workload": f"{n_hollow}hollow_{n_pods}pods"
                                f"_{outage_s}s_outage",
                    "outage_s": outage_s, "grace_s": grace_s,
                    "data_dir_mode": True}
    failures: list[str] = []
    proc = cluster = runner = ctrl = factory = None
    churn_stop = threading.Event()
    try:
        proc = ApiServerProcess(data_dir=data_dir)
        proc.start()
        result["readyz_cold_s"] = round(proc.wait_ready(60.0), 3)
        url = proc.url

        t0 = time.time()
        cluster = HollowCluster(
            HTTPClient(url, timeout=60.0), n_hollow, prefix="dz",
            heartbeat_period=heartbeat_period).start(wait_sync=60.0)
        result["register_s"] = round(time.time() - t0, 2)
        log(f"  {n_hollow} hollow nodes registered in "
            f"{result['register_s']}s")

        # node lifecycle with DISRUPTION PROTECTION: the outage makes
        # every lease stale past grace at once — exactly the mass-unready
        # signal the partial/full-disruption modes exist to distrust
        ctrl = NodeLifecycleController(
            HTTPClient(url, timeout=30.0), grace_period=grace_s,
            monitor_period=0.5)
        factory = InformerFactory(ctrl.client)
        ctrl.register(factory)
        factory.start_all()
        assert factory.wait_for_cache_sync(30.0)
        ctrl.start()

        runner = SchedulerRunner(
            HTTPClient(url),
            SchedulerConfiguration(batch_size=64, max_drain_batches=2))
        runner.auditor = _bench_auditor(runner, HTTPClient(url))
        runner.start(wait_sync=60.0)

        client = HTTPClient(url, timeout=60.0)
        pods = [make_pod(f"dz-{i}", "default")
                .req({"cpu": "100m", "memory": "64Mi"}).obj().to_dict()
                for i in range(n_pods)]
        t_bind = time.time()
        client.pods("default").create_many(pods)
        deadline = t_bind + timeout
        while time.time() < deadline:
            if not _unbound(client, ("default",)):
                break
            time.sleep(0.25)
        result["initial_bind_s"] = round(time.time() - t_bind, 2)
        log(f"  initial {n_pods} pods bound at "
            f"+{result['initial_bind_s']}s")

        churn_stats: dict = {}
        threading.Thread(target=_pod_churn_loop,
                         args=(HTTPClient(url, timeout=30.0), churn_stop,
                               churn_stats),
                         daemon=True).start()
        time.sleep(4.0)  # churn warm-up: steady state before the crash

        # ---- the disaster -----------------------------------------------
        evictions_before = ctrl.evictions
        engaged_before = ctrl.engaged_count
        log(f"  SIGKILL apiserver (pid alive={proc.alive}); "
            f"outage {outage_s}s ...")
        t_kill = time.time()
        proc.kill()
        time.sleep(outage_s)
        modes_during = ctrl.mode
        try:
            restart_ready_s = proc.restart(ready_timeout=60.0)
            result["readyz_restart_s"] = round(restart_ready_s, 3)
        except Exception as e:
            failures.append(f"restart never reached /readyz 200: {e}")
            raise
        result["outage_total_s"] = round(time.time() - t_kill, 2)
        log(f"  restarted from WAL in {result['readyz_restart_s']}s "
            f"(mode during outage: {modes_during})")

        # time-to-first-bind-after-restart: a fresh probe pod through the
        # full heal path (informer relist -> queue -> drain -> bind)
        probe = make_pod("probe-restart", "default").req(
            {"cpu": "100m"}).obj().to_dict()
        t_probe = time.time()
        probe_client = HTTPClient(url, timeout=30.0, retry_attempts=6)
        probe_client.pods("default").create(probe)
        bound_at = None
        while time.time() - t_probe < max(bind_slo_s * 3, 30.0):
            try:
                p = probe_client.pods("default").get("probe-restart")
            except Exception:
                time.sleep(0.2)  # reconnect blip; the poll budget absorbs it
                continue
            if (p.get("spec") or {}).get("nodeName"):
                bound_at = time.time() - t_probe
                break
            time.sleep(0.2)
        result["first_bind_after_restart_s"] = (
            round(bound_at, 2) if bound_at is not None else None)
        log(f"  probe pod bound {result['first_bind_after_restart_s']}s "
            "after restart")

        # ---- heal + settle ----------------------------------------------
        settle_deadline = time.time() + settle_timeout
        while time.time() < settle_deadline and ctrl.mode != MODE_NORMAL:
            time.sleep(0.5)
        churn_stop.set()
        time.sleep(1.0)
        while time.time() < settle_deadline:
            # converged = every pod bound AND no lifecycle taint residue
            # (a 409-delayed taint removal retries on the next sweep —
            # give it the chance instead of failing on a snapshot race)
            if not _unbound(client) and not _lifecycle_taints(client):
                break
            time.sleep(0.5)
        unbound = _unbound(client)
        result["unbound"] = unbound[:20]
        result["churn_api_ops"] = churn_stats.get("ops", 0)
        result["churn_errors"] = churn_stats.get("errors", 0)
        result["fleet"] = cluster.fleet_stats()
        result["disruption"] = ctrl.disruption_status()
        taints = _lifecycle_taints(client)
        result["lifecycle_taints"] = taints[:20]
        result["outage_evictions"] = ctrl.evictions - evictions_before
        result.update(_audit_close(runner))

        # ---- the gates (missing number = failure) -----------------------
        if unbound:
            failures.append(f"{len(unbound)} pods never bound after the "
                            f"restart (first: {unbound[:5]})")
        fb = result["first_bind_after_restart_s"]
        if not isinstance(fb, (int, float)):
            failures.append("time-to-first-bind-after-restart missing — "
                            "the probe pod never bound")
        elif fb > bind_slo_s:
            failures.append(f"first bind after restart took {fb}s "
                            f"(gate {bind_slo_s}s)")
        if result["outage_evictions"]:
            failures.append(f"{result['outage_evictions']} outage-caused "
                            "evictions (disruption mode failed)")
        if taints:
            failures.append(f"lifecycle taints survived the heal: "
                            f"{taints[:5]}")
        if ctrl.engaged_count <= engaged_before:
            failures.append("disruption mode never engaged — the outage "
                            "was not observed as mass-unready (protection "
                            "untested = failure)")
        if ctrl.mode != MODE_NORMAL:
            failures.append(f"disruption mode never released "
                            f"(still {ctrl.mode})")
        if result.get("invariant_violations"):
            failures.append(f"{result['invariant_violations']} confirmed "
                            "invariant violations")
        if "readyz_restart_s" not in result:
            failures.append("readyz-after-restart missing")
    except Exception as e:  # a dead bench must fail loudly, not silently
        failures.append(f"bench crashed: {type(e).__name__}: {e}")
        result.setdefault("invariant_violations", None)
    finally:
        churn_stop.set()
        for closer in (
                (lambda: runner.stop()) if runner is not None else None,
                (lambda: ctrl.stop()) if ctrl is not None else None,
                (lambda: factory.stop_all()) if factory is not None else None,
                (lambda: cluster.stop()) if cluster is not None else None,
                (lambda: proc.stop()) if proc is not None else None):
            if closer is not None:
                try:
                    closer()
                except Exception:
                    pass
        shutil.rmtree(data_dir, ignore_errors=True)
    result["slo_failures"] = failures
    return result


def run_scheduler_kill(n_nodes: int = 16, n_pods: int = 48,
                       churn_s: float = 4.0, bind_slo_s: float = 3.0,
                       settle_timeout: float = 120.0,
                       timeout: float = 240.0,
                       ready_timeout: float = 300.0,
                       log=lambda *a: None) -> dict:
    """The scheduler dies under churn; its successor must boot warm.

    The apiserver survives (in-process, stable port, durable data_dir);
    a SchedulerProcess child — AOT cache dir on the same durable disk,
    fail-fast auditor at a 1s cadence — binds an initial workload, cold
    boot populating the executable cache. Mid pod-churn the child is
    SIGKILLed and restarted; the successor's boot report must show
    entries loaded from disk, and its gates (read over the pipe from the
    CHILD's own meters) are hard:

      - first bind <= ``bind_slo_s`` after the restarted loop is live
      - ZERO genuine XLA compiles in the child (realCompiles, compile
        meter; missing number = failure)
      - persistent-cache hits > 0 (a zero-compile claim with zero hits
        means nothing device-shaped ran — untested protection = failure)
      - 0 confirmed invariant violations, no pod lost or left unbound
        (covers duplicate binds and stale-state mistakes post-resync)
    """
    from kubernetes_tpu.chaos.apiserver import InProcessApiServer
    from kubernetes_tpu.chaos.scheduler import SchedulerProcess
    from kubernetes_tpu.client.clientset import HTTPClient
    from kubernetes_tpu.testing.wrappers import make_node, make_pod

    data_dir = tempfile.mkdtemp(prefix="ktpu-schedkill-")
    result: dict = {"case": "SchedulerKill",
                    "workload": f"{n_nodes}nodes_{n_pods}pods"}
    failures: list[str] = []
    server = sched = None
    churn_stop = threading.Event()
    try:
        server = InProcessApiServer(data_dir=os.path.join(data_dir, "api"))
        server.start()
        url = server.url
        seed_client = HTTPClient(url, timeout=60.0)
        seed_client.nodes().create_many([
            make_node(f"sk-n{i}").capacity(
                {"cpu": "8", "memory": "16Gi", "pods": "64"}).obj().to_dict()
            for i in range(n_nodes)])

        sched = SchedulerProcess(
            url,
            cfg={"aotCacheDir": os.path.join(data_dir, "aot-cache"),
                 "auditFailFast": True, "auditIntervalSeconds": 1.0,
                 "batchSize": 16,
                 "backoffInitialSeconds": 0.05, "backoffMaxSeconds": 0.5},
            warm={"pods": 16, "requests": {"cpu": "100m",
                                           "memory": "64Mi"}})
        t0 = time.time()
        ready_cold = sched.start(ready_timeout=ready_timeout)
        result["cold_boot_s"] = round(time.time() - t0, 2)
        result["cold_ready"] = ready_cold
        log(f"  cold scheduler boot {result['cold_boot_s']}s "
            f"(warm ladder {ready_cold['warmMs']}ms, cache boot "
            f"{ready_cold.get('aotCacheBoot')})")

        t_bind = time.time()
        seed_client.pods("default").create_many(
            [make_pod(f"sk-{i}", "default")
             .req({"cpu": "100m", "memory": "64Mi"}).obj().to_dict()
             for i in range(n_pods)])
        deadline = t_bind + timeout
        while time.time() < deadline:
            if not _unbound(seed_client, ("default",)):
                break
            time.sleep(0.2)
        result["initial_bind_s"] = round(time.time() - t_bind, 2)
        log(f"  initial {n_pods} pods bound at "
            f"+{result['initial_bind_s']}s")

        churn_stats: dict = {}
        threading.Thread(target=_pod_churn_loop,
                         args=(HTTPClient(url, timeout=30.0), churn_stop,
                               churn_stats),
                         daemon=True).start()
        time.sleep(churn_s / 2)

        # Compile quiescence before the kill: churn-driven shape buckets
        # (patch write widths, mostly) compile lazily, and jax persists
        # each entry only when its compile finishes — killing mid-ladder
        # would test an incomplete cache, which is a different (weaker)
        # claim than the one gated here: a STEADY-STATE scheduler's
        # restart is zero-compile. Poll the child's meter until the entry
        # set and compile count stop moving.
        prev = None
        quiesce_deadline = time.time() + 30.0
        while time.time() < quiesce_deadline:
            s = sched.stats()
            cur = (s["aotCache"].get("entries"),
                   s["aotCache"].get("realCompiles"))
            if cur == prev:
                break
            prev = cur
            time.sleep(0.7)
        result["steady_cache_entries"] = prev[0] if prev else None

        # ---- the disaster -----------------------------------------------
        log(f"  SIGKILL scheduler (pid alive={sched.alive}) mid-churn, "
            f"{prev[0] if prev else '?'} entries persisted ...")
        sched.kill()
        time.sleep(churn_s / 2)  # churn piles up against no scheduler
        try:
            restart_s = sched.restart(ready_timeout=ready_timeout)
        except Exception as e:
            failures.append(f"scheduler restart never became ready: {e}")
            raise
        ready_warm = sched.ready
        result["restart_total_s"] = round(restart_s, 2)
        result["warm_ready"] = ready_warm
        cache_boot = ready_warm.get("aotCacheBoot") or {}
        result["warm_boot_entries"] = cache_boot.get("entries")
        log(f"  scheduler restarted in {restart_s:.1f}s total; warm "
            f"ladder {ready_warm['warmMs']}ms from "
            f"{cache_boot.get('entries')} cached entries "
            f"({cache_boot.get('loadMs')}ms cache load)")

        # first bind after the restarted loop is live: a fresh probe pod
        # through the full path (informer -> queue -> drain -> bind)
        probe = make_pod("probe-schedkill", "default").req(
            {"cpu": "100m"}).obj().to_dict()
        t_probe = time.time()
        seed_client.pods("default").create(probe)
        bound_at = None
        while time.time() - t_probe < max(bind_slo_s * 5, 30.0):
            p = seed_client.pods("default").get("probe-schedkill")
            if (p.get("spec") or {}).get("nodeName"):
                bound_at = time.time() - t_probe
                break
            time.sleep(0.1)
        result["first_bind_after_restart_s"] = (
            round(bound_at, 2) if bound_at is not None else None)
        log(f"  probe pod bound {result['first_bind_after_restart_s']}s "
            "after restart-ready")

        # The zero-compile gate reads the meter NOW — the recovery window
        # (activation -> warm ladder -> loop -> first bind) is what the
        # cache promises is compile-free. Churn after this point may
        # legitimately surface a shape bucket the predecessor never saw.
        try:
            recovery = sched.stats()
            result["recovery_stats"] = recovery
        except Exception as e:
            recovery = {}
            failures.append(f"recovery-window stats unavailable: {e} — "
                            "the zero-compile gate is unverifiable")
        cache_stats = recovery.get("aotCache") or {}

        # ---- settle + the child's end-state numbers ---------------------
        churn_stop.set()
        time.sleep(1.0)
        settle_deadline = time.time() + settle_timeout
        while time.time() < settle_deadline:
            if not _unbound(seed_client):
                break
            time.sleep(0.25)
        unbound = _unbound(seed_client)
        result["unbound"] = unbound[:20]
        result["churn_api_ops"] = churn_stats.get("ops", 0)
        result["churn_errors"] = churn_stats.get("errors", 0)
        try:
            stats = sched.stats()
            result["child_stats"] = stats
        except Exception as e:
            stats = {}
            failures.append(f"child stats unavailable: {e} — every gate "
                            "below it is unverifiable")
        result["invariant_violations"] = stats.get("violations")

        # ---- the gates (missing number = failure) -----------------------
        if unbound:
            failures.append(f"{len(unbound)} pods never bound after the "
                            f"scheduler restart (first: {unbound[:5]})")
        fb = result["first_bind_after_restart_s"]
        if not isinstance(fb, (int, float)):
            failures.append("time-to-first-bind-after-restart missing — "
                            "the probe pod never bound")
        elif fb > bind_slo_s:
            failures.append(f"first bind after restart took {fb}s "
                            f"(gate {bind_slo_s}s)")
        if not isinstance(result["warm_boot_entries"], int) \
                or result["warm_boot_entries"] < 1:
            failures.append("restarted scheduler loaded no cached "
                            "executables — the warm-from-birth path "
                            "never ran (untested protection = failure)")
        rc = cache_stats.get("realCompiles")
        if not isinstance(rc, int):
            failures.append("genuine-compile count missing from the "
                            "restarted child (zero-compile gate "
                            "unverifiable = failure)")
        elif rc > 0:
            failures.append(f"{rc} genuine XLA compiles in the recovery "
                            "window (gate: 0 — the executable cache "
                            "missed)")
        if prev is not None and isinstance(prev[1], int) and prev[1] == 0:
            failures.append("the COLD child reported 0 genuine compiles — "
                            "the meter is not seeing compiles, so the "
                            "warm child's 0 proves nothing")
        if not cache_stats.get("hits"):
            failures.append("0 persistent-cache hits in the restarted "
                            "child — nothing loaded from disk, the "
                            "zero-compile number proves nothing")
        if cache_stats.get("bootLoadMs") is None:
            failures.append("cache boot-load timing missing")
        if stats.get("violations") != 0:
            failures.append(f"invariant violations in the restarted "
                            f"child: {stats.get('violations')!r} "
                            "(gate: 0)")
        if stats.get("auditFailed"):
            failures.append("the child's fail-fast auditor tripped")
        if (stats.get("parity") or {}).get("divergences"):
            failures.append("parity divergence: a cached executable gave "
                            "a wrong answer")
    except Exception as e:  # a dead bench must fail loudly, not silently
        failures.append(f"bench crashed: {type(e).__name__}: {e}")
        result.setdefault("invariant_violations", None)
    finally:
        churn_stop.set()
        for closer in (
                (lambda: sched.stop()) if sched is not None else None,
                (lambda: server.stop()) if server is not None else None):
            if closer is not None:
                try:
                    closer()
                except Exception:
                    pass
        shutil.rmtree(data_dir, ignore_errors=True)
    result["slo_failures"] = failures
    return result


if __name__ == "__main__":
    import json
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    _log = lambda *a: print(*a, file=sys.stderr)
    case = os.environ.get("BENCH_DISASTER_CASE", "apiserver")
    if case == "scheduler-kill":
        res = run_scheduler_kill(
            n_nodes=int(os.environ.get("BENCH_DISASTER_NODES", "16")),
            n_pods=int(os.environ.get("BENCH_DISASTER_PODS", "48")),
            bind_slo_s=float(os.environ.get(
                "BENCH_SCHED_KILL_BIND_SLO", "3")),
            log=_log)
    else:
        res = run_disaster_churn(
            n_hollow=int(os.environ.get("BENCH_DISASTER_NODES", "48")),
            n_pods=int(os.environ.get("BENCH_DISASTER_PODS", "96")),
            outage_s=float(os.environ.get("BENCH_DISASTER_OUTAGE_S", "16")),
            bind_slo_s=float(os.environ.get("BENCH_DISASTER_BIND_SLO",
                                            "10")),
            log=_log)
    print(json.dumps(res))
    if res.get("slo_failures") or res.get("invariant_violations"):
        sys.exit(1)
