"""DisasterChurn: the apiserver dies (SIGKILL) under live churn and the
whole stack survives its restart.

The canonical control-plane robustness scenario (upstream treats
etcd/apiserver restart + mass node-unready fallout as exactly this): a
hollow fleet heartbeats and runs pods, the scheduler binds a sustained
churn stream, the node-lifecycle controller watches for staleness — and
mid-window the apiserver subprocess is SIGKILLed, then restarted from
the SAME ``data_dir`` (WAL + snapshot replay, ``/readyz`` 503 until
done) on the SAME port. Every layer must heal through its own
discipline: HTTPClient full-jitter backoff absorbs the refused-
connection storm, informers relist (410/TooOld on pre-restart rvs),
fleet batchers back off + re-coalesce + re-assert on reconnect, and the
node-lifecycle disruption mode keeps the fleet-wide lease staleness the
outage manufactured from cascading into a taint/evict storm.

Hard gates (missing number = failure, the PR-8 SLO discipline):
  - every pod that exists at the end is BOUND (none lost, none stuck)
  - 0 confirmed invariant violations (fail-fast auditor live throughout)
  - 0 outage-caused evictions, 0 lifecycle taints left on any node —
    with the disruption mode provably ENGAGED during the outage and
    RELEASED after heal (protection that never fires protects nothing)
  - time-to-first-bind-after-restart <= ``bind_slo_s`` (default 10s)
  - the restarted server reached /readyz 200 (replay completed)
"""

from __future__ import annotations

import shutil
import tempfile
import threading
import time


def _pod_churn_loop(client, stop, counter, period_s: float = 0.25) -> None:
    """Sustained pod churn (namespace ``churn``): a rolling window of
    short-lived pods. Errors are EXPECTED mid-outage (the apiserver is
    dead); the loop keeps trying and counts what committed."""
    import itertools

    from kubernetes_tpu.testing.wrappers import make_pod
    seq = itertools.count()
    live: list = []
    while not stop.is_set():
        i = next(seq)
        try:
            pod = make_pod(f"churn-p{i}", "churn").req(
                {"cpu": "100m"}).obj()
            client.pods("churn").create(pod.to_dict())
            live.append(pod.metadata.name)
            if len(live) > 4:
                client.pods("churn").delete(live.pop(0))
            counter["ops"] = counter.get("ops", 0) + 2
        except Exception:
            counter["errors"] = counter.get("errors", 0) + 1
        stop.wait(period_s)


def _unbound(client, namespaces=("default", "churn")) -> list[str]:
    out = []
    for ns in namespaces:
        for p in client.pods(ns).list():
            if not (p.get("spec") or {}).get("nodeName"):
                out.append(f"{ns}/{p['metadata']['name']}")
    return out


def _lifecycle_taints(client) -> list[str]:
    from kubernetes_tpu.controllers.nodelifecycle import (
        TAINT_NOT_READY, TAINT_UNREACHABLE)
    out = []
    for n in client.nodes().list():
        for t in (n.get("spec") or {}).get("taints") or []:
            if t.get("key") in (TAINT_NOT_READY, TAINT_UNREACHABLE):
                out.append(f"{n['metadata']['name']}:{t['key']}")
    return out


def run_disaster_churn(n_hollow: int = 48, n_pods: int = 96,
                       outage_s: float = 16.0, grace_s: float = 12.0,
                       heartbeat_period: float = 1.0,
                       bind_slo_s: float = 10.0,
                       settle_timeout: float = 120.0,
                       timeout: float = 240.0,
                       log=lambda *a: None) -> dict:
    from benchmarks.connected import _audit_close, _bench_auditor
    from kubernetes_tpu.chaos.apiserver import ApiServerProcess
    from kubernetes_tpu.client.clientset import HTTPClient
    from kubernetes_tpu.client.informer import InformerFactory
    from kubernetes_tpu.config.types import SchedulerConfiguration
    from kubernetes_tpu.controllers.nodelifecycle import (
        MODE_NORMAL, NodeLifecycleController)
    from kubernetes_tpu.kubelet.kubemark import HollowCluster
    from kubernetes_tpu.sched.runner import SchedulerRunner
    from kubernetes_tpu.testing.wrappers import make_pod

    # grace must clear the fleet's lease cadence (min(10, hb*5)) with
    # margin, or steady state itself flaps unready under suite load
    lease_period = min(10.0, heartbeat_period * 5)
    assert grace_s > 2 * lease_period, \
        f"grace {grace_s}s too tight for lease period {lease_period}s"

    data_dir = tempfile.mkdtemp(prefix="ktpu-disaster-")
    result: dict = {"case": "DisasterChurn",
                    "workload": f"{n_hollow}hollow_{n_pods}pods"
                                f"_{outage_s}s_outage",
                    "outage_s": outage_s, "grace_s": grace_s,
                    "data_dir_mode": True}
    failures: list[str] = []
    proc = cluster = runner = ctrl = factory = None
    churn_stop = threading.Event()
    try:
        proc = ApiServerProcess(data_dir=data_dir)
        proc.start()
        result["readyz_cold_s"] = round(proc.wait_ready(60.0), 3)
        url = proc.url

        t0 = time.time()
        cluster = HollowCluster(
            HTTPClient(url, timeout=60.0), n_hollow, prefix="dz",
            heartbeat_period=heartbeat_period).start(wait_sync=60.0)
        result["register_s"] = round(time.time() - t0, 2)
        log(f"  {n_hollow} hollow nodes registered in "
            f"{result['register_s']}s")

        # node lifecycle with DISRUPTION PROTECTION: the outage makes
        # every lease stale past grace at once — exactly the mass-unready
        # signal the partial/full-disruption modes exist to distrust
        ctrl = NodeLifecycleController(
            HTTPClient(url, timeout=30.0), grace_period=grace_s,
            monitor_period=0.5)
        factory = InformerFactory(ctrl.client)
        ctrl.register(factory)
        factory.start_all()
        assert factory.wait_for_cache_sync(30.0)
        ctrl.start()

        runner = SchedulerRunner(
            HTTPClient(url),
            SchedulerConfiguration(batch_size=64, max_drain_batches=2))
        runner.auditor = _bench_auditor(runner, HTTPClient(url))
        runner.start(wait_sync=60.0)

        client = HTTPClient(url, timeout=60.0)
        pods = [make_pod(f"dz-{i}", "default")
                .req({"cpu": "100m", "memory": "64Mi"}).obj().to_dict()
                for i in range(n_pods)]
        t_bind = time.time()
        client.pods("default").create_many(pods)
        deadline = t_bind + timeout
        while time.time() < deadline:
            if not _unbound(client, ("default",)):
                break
            time.sleep(0.25)
        result["initial_bind_s"] = round(time.time() - t_bind, 2)
        log(f"  initial {n_pods} pods bound at "
            f"+{result['initial_bind_s']}s")

        churn_stats: dict = {}
        threading.Thread(target=_pod_churn_loop,
                         args=(HTTPClient(url, timeout=30.0), churn_stop,
                               churn_stats),
                         daemon=True).start()
        time.sleep(4.0)  # churn warm-up: steady state before the crash

        # ---- the disaster -----------------------------------------------
        evictions_before = ctrl.evictions
        engaged_before = ctrl.engaged_count
        log(f"  SIGKILL apiserver (pid alive={proc.alive}); "
            f"outage {outage_s}s ...")
        t_kill = time.time()
        proc.kill()
        time.sleep(outage_s)
        modes_during = ctrl.mode
        try:
            restart_ready_s = proc.restart(ready_timeout=60.0)
            result["readyz_restart_s"] = round(restart_ready_s, 3)
        except Exception as e:
            failures.append(f"restart never reached /readyz 200: {e}")
            raise
        result["outage_total_s"] = round(time.time() - t_kill, 2)
        log(f"  restarted from WAL in {result['readyz_restart_s']}s "
            f"(mode during outage: {modes_during})")

        # time-to-first-bind-after-restart: a fresh probe pod through the
        # full heal path (informer relist -> queue -> drain -> bind)
        probe = make_pod("probe-restart", "default").req(
            {"cpu": "100m"}).obj().to_dict()
        t_probe = time.time()
        probe_client = HTTPClient(url, timeout=30.0, retry_attempts=6)
        probe_client.pods("default").create(probe)
        bound_at = None
        while time.time() - t_probe < max(bind_slo_s * 3, 30.0):
            try:
                p = probe_client.pods("default").get("probe-restart")
            except Exception:
                time.sleep(0.2)  # reconnect blip; the poll budget absorbs it
                continue
            if (p.get("spec") or {}).get("nodeName"):
                bound_at = time.time() - t_probe
                break
            time.sleep(0.2)
        result["first_bind_after_restart_s"] = (
            round(bound_at, 2) if bound_at is not None else None)
        log(f"  probe pod bound {result['first_bind_after_restart_s']}s "
            "after restart")

        # ---- heal + settle ----------------------------------------------
        settle_deadline = time.time() + settle_timeout
        while time.time() < settle_deadline and ctrl.mode != MODE_NORMAL:
            time.sleep(0.5)
        churn_stop.set()
        time.sleep(1.0)
        while time.time() < settle_deadline:
            # converged = every pod bound AND no lifecycle taint residue
            # (a 409-delayed taint removal retries on the next sweep —
            # give it the chance instead of failing on a snapshot race)
            if not _unbound(client) and not _lifecycle_taints(client):
                break
            time.sleep(0.5)
        unbound = _unbound(client)
        result["unbound"] = unbound[:20]
        result["churn_api_ops"] = churn_stats.get("ops", 0)
        result["churn_errors"] = churn_stats.get("errors", 0)
        result["fleet"] = cluster.fleet_stats()
        result["disruption"] = ctrl.disruption_status()
        taints = _lifecycle_taints(client)
        result["lifecycle_taints"] = taints[:20]
        result["outage_evictions"] = ctrl.evictions - evictions_before
        result.update(_audit_close(runner))

        # ---- the gates (missing number = failure) -----------------------
        if unbound:
            failures.append(f"{len(unbound)} pods never bound after the "
                            f"restart (first: {unbound[:5]})")
        fb = result["first_bind_after_restart_s"]
        if not isinstance(fb, (int, float)):
            failures.append("time-to-first-bind-after-restart missing — "
                            "the probe pod never bound")
        elif fb > bind_slo_s:
            failures.append(f"first bind after restart took {fb}s "
                            f"(gate {bind_slo_s}s)")
        if result["outage_evictions"]:
            failures.append(f"{result['outage_evictions']} outage-caused "
                            "evictions (disruption mode failed)")
        if taints:
            failures.append(f"lifecycle taints survived the heal: "
                            f"{taints[:5]}")
        if ctrl.engaged_count <= engaged_before:
            failures.append("disruption mode never engaged — the outage "
                            "was not observed as mass-unready (protection "
                            "untested = failure)")
        if ctrl.mode != MODE_NORMAL:
            failures.append(f"disruption mode never released "
                            f"(still {ctrl.mode})")
        if result.get("invariant_violations"):
            failures.append(f"{result['invariant_violations']} confirmed "
                            "invariant violations")
        if "readyz_restart_s" not in result:
            failures.append("readyz-after-restart missing")
    except Exception as e:  # a dead bench must fail loudly, not silently
        failures.append(f"bench crashed: {type(e).__name__}: {e}")
        result.setdefault("invariant_violations", None)
    finally:
        churn_stop.set()
        for closer in (
                (lambda: runner.stop()) if runner is not None else None,
                (lambda: ctrl.stop()) if ctrl is not None else None,
                (lambda: factory.stop_all()) if factory is not None else None,
                (lambda: cluster.stop()) if cluster is not None else None,
                (lambda: proc.stop()) if proc is not None else None):
            if closer is not None:
                try:
                    closer()
                except Exception:
                    pass
        shutil.rmtree(data_dir, ignore_errors=True)
    result["slo_failures"] = failures
    return result


if __name__ == "__main__":
    import json
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    res = run_disaster_churn(
        n_hollow=int(os.environ.get("BENCH_DISASTER_NODES", "48")),
        n_pods=int(os.environ.get("BENCH_DISASTER_PODS", "96")),
        outage_s=float(os.environ.get("BENCH_DISASTER_OUTAGE_S", "16")),
        bind_slo_s=float(os.environ.get("BENCH_DISASTER_BIND_SLO", "10")),
        log=lambda *a: print(*a, file=sys.stderr))
    print(json.dumps(res))
    if res.get("slo_failures") or res.get("invariant_violations"):
        sys.exit(1)
