"""Domain-count hot-op benchmark — the measurement that retired the Pallas
kernel (VERDICT r3 ask #6: prove or delete).

History: ``ops/pallas/domain_count.py`` fused the [E,P,T] selector match
with the per-node count so the match tensor never left VMEM. Measured on
the real v5e chip (round 4, forced per-iteration materialization so async
dispatch could not flatter either side, 16384 epods x 1024 pods x 4 terms x
5120 nodes):

    XLA match+einsum : ~122 ms/eval
    Pallas kernel    : ~14,712 ms/eval  (120x SLOWER)

Root causes: at MXU-friendly tiles (128/128/256) Mosaic's register
allocator spilled ~74 MiB of VMEM stack (fixable via
CompilerParams.vmem_limit_bytes), but even then the 82k-step grid of tiny
HIGHEST-precision dots starved the MXU while XLA fuses the same chain into
a handful of large contractions. The kernel was deleted; this benchmark
keeps the LIVE number for the XLA path that won.

Run on the real chip:
    python benchmarks/pallas_bench.py [E] [P] [T]

Prints one JSON line with the live xla_ms and the recorded comparison.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

# the round-4 measurement that decided deletion (see module docstring)
RETIRED_KERNEL = {
    "status": "deleted_round4_lost_to_xla",
    "measured_on": "v5e (axon), forced materialization, 16384x1024x4x5120",
    "pallas_ms": 14712.0,
    "xla_ms": 122.0,
    "pallas_vs_xla": 0.0083,
}


def run_domain_count(E: int = 16384, P: int = 1024, T: int = 4) -> dict:
    N, K, X, V = 5120, 64, 4, 2
    rng = np.random.default_rng(0)

    epod_labels = jnp.asarray(rng.integers(-1, 32, (E, K)), jnp.int32)
    epod_node = jnp.asarray(rng.integers(0, N, E), jnp.int32)
    epod_ns = jnp.asarray(rng.integers(0, 4, E), jnp.int32)
    epod_valid = jnp.ones(E, bool)
    sel_key = jnp.asarray(rng.integers(0, K, (P, T, X)), jnp.int32)
    sel_op = jnp.asarray(rng.integers(0, 4, (P, T, X)), jnp.int32)
    sel_ev = jnp.ones((P, T, X), bool)
    sel_vals = jnp.asarray(rng.integers(-1, 32, (P, T, X, V)), jnp.int32)
    sel_valid = jnp.ones((P, T), bool)
    pod_ns = jnp.asarray(rng.integers(0, 4, P), jnp.int32)

    from kubernetes_tpu.encode.snapshot import SelectorSet
    from kubernetes_tpu.ops.exprs import eval_selector_set

    sel = SelectorSet(key=sel_key, op=sel_op, vals=sel_vals,
                      expr_valid=sel_ev, valid=sel_valid)

    @jax.jit
    def xla_path(labels, node, ns, valid, pns, salt):
        # salt defeats any same-args result reuse in remote runtimes; the
        # scalar sum forces full materialization before the clock stops
        m = eval_selector_set(sel, labels + salt - salt)     # [E,P,T]
        ns_ok = ns[:, None] == pns[None, :]
        m = (m & ns_ok[:, :, None] & valid[:, None, None]).astype(jnp.float32)
        onehot = (node[:, None] == jnp.arange(N)[None, :]).astype(jnp.float32)
        return jnp.sum(jnp.einsum("ept,en->ptn", m, onehot))

    args = (epod_labels, epod_node, epod_ns, epod_valid, pod_ns)
    float(xla_path(*args, jnp.int32(0)))  # compile
    iters = 10
    t0 = time.perf_counter()
    for i in range(iters):
        float(xla_path(*args, jnp.int32(i)))
    t_xla = (time.perf_counter() - t0) / iters
    return {
        "metric": "domain_count_hot_op",
        "backend": jax.default_backend(),
        "shape": {"E": E, "P": P, "T": T, "N": N},
        "xla_ms": round(t_xla * 1e3, 3),
        "retired_pallas_kernel": RETIRED_KERNEL,
    }


def main():
    print(json.dumps(run_domain_count(
        E=int(sys.argv[1]) if len(sys.argv) > 1 else 16384,
        P=int(sys.argv[2]) if len(sys.argv) > 2 else 1024,
        T=int(sys.argv[3]) if len(sys.argv) > 3 else 4)))


if __name__ == "__main__":
    main()
