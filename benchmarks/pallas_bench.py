"""Micro-benchmark: fused Pallas selector-match+count kernel vs the XLA
match+einsum pair (ops/pallas/domain_count.py vs ops/topology.py fallback).

Run on the real chip:
    python benchmarks/pallas_bench.py [E] [P] [T]

Prints one JSON line: both timings and the speedup. The shapes default to a
large-cluster scheduling step (16k existing pods, 1k-pod batch, 4 terms,
5k nodes) where the XLA path's [E,P,T] f32 intermediate is ~256 MB of HBM
round-trip per evaluation.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def bench(fn, *args, iters=20):
    out = fn(*args)
    jax.block_until_ready(out)  # compile
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def main():
    E = int(sys.argv[1]) if len(sys.argv) > 1 else 16384
    P = int(sys.argv[2]) if len(sys.argv) > 2 else 1024
    T = int(sys.argv[3]) if len(sys.argv) > 3 else 4
    N, K, X, V, NSB = 5120, 64, 4, 2, 8
    rng = np.random.default_rng(0)

    epod_labels = jnp.asarray(
        rng.integers(-1, 32, (E, K)), jnp.int32)
    epod_node = jnp.asarray(rng.integers(0, N, E), jnp.int32)
    epod_ns = jnp.asarray(rng.integers(0, 4, E), jnp.int32)
    epod_valid = jnp.ones(E, bool)
    sel_key = jnp.asarray(rng.integers(0, K, (P, T, X)), jnp.int32)
    sel_op = jnp.asarray(rng.integers(0, 4, (P, T, X)), jnp.int32)
    sel_ev = jnp.ones((P, T, X), bool)
    sel_vals = jnp.asarray(rng.integers(-1, 32, (P, T, X, V)), jnp.int32)
    sel_valid = jnp.ones((P, T), bool)
    pod_ns = jnp.asarray(rng.integers(0, 4, P), jnp.int32)
    ns_explicit = jnp.zeros((P, T), bool)
    ns_mask = jnp.zeros((P, T, NSB), bool)

    from kubernetes_tpu.encode.snapshot import SelectorSet
    from kubernetes_tpu.ops.exprs import eval_selector_set
    from kubernetes_tpu.ops.pallas.domain_count import match_count

    sel = SelectorSet(key=sel_key, op=sel_op, vals=sel_vals,
                      expr_valid=sel_ev, valid=sel_valid)

    @jax.jit
    def xla_path(labels, node, ns, valid, pns):
        m = eval_selector_set(sel, labels)                   # [E,P,T]
        ns_ok = ns[:, None] == pns[None, :]
        m = (m & ns_ok[:, :, None] & valid[:, None, None]).astype(jnp.float32)
        onehot = (node[:, None] == jnp.arange(N)[None, :]).astype(jnp.float32)
        return jnp.einsum("ept,en->ptn", m, onehot)

    def pallas_path(labels, node, ns, valid, pns):
        return match_count(labels, node, ns, valid, sel_key, sel_op, sel_ev,
                           sel_vals, sel_valid, pns, ns_explicit=ns_explicit,
                           ns_mask=ns_mask, n_nodes=N)

    args = (epod_labels, epod_node, epod_ns, epod_valid, pod_ns)
    t_xla = bench(xla_path, *args)
    try:
        t_pal = bench(pallas_path, *args)
        # correctness spot-check on the bench shapes
        diff = float(jnp.max(jnp.abs(xla_path(*args) - pallas_path(*args))))
        ok = diff == 0.0
    except Exception as e:  # kernel unavailable on this backend
        t_pal, ok = float("nan"), False
        print(f"pallas path failed: {e}", file=sys.stderr)
    print(json.dumps({
        "metric": "fused_domain_count_speedup",
        "backend": jax.default_backend(),
        "shape": {"E": E, "P": P, "T": T, "N": N},
        "xla_ms": round(t_xla * 1e3, 3),
        "pallas_ms": round(t_pal * 1e3, 3) if t_pal == t_pal else None,
        "speedup": round(t_xla / t_pal, 3) if t_pal == t_pal else None,
        "bit_exact": ok,
    }))


if __name__ == "__main__":
    main()
