"""Wall-clock breakdown of the connected run: tracer spans + bind timing.
Diagnostic tool, not part of the bench suite."""
import collections
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kubernetes_tpu.utils.tracing import TRACER
from kubernetes_tpu.sched.scheduler import Scheduler
from kubernetes_tpu.sched.runner import SchedulerRunner

# instrument _bind_one and runner._bind
bind_stats = {"n": 0, "t": 0.0}
orig_bind_one = Scheduler._bind_one


def timed_bind_one(self, pod, node_name):
    t0 = time.time()
    try:
        return orig_bind_one(self, pod, node_name)
    finally:
        bind_stats["n"] += 1
        bind_stats["t"] += time.time() - t0


Scheduler._bind_one = timed_bind_one

run_stats = {"n": 0, "t": 0.0, "assume_t": 0.0}
orig_run_once = Scheduler.run_once


def timed_run_once(self, wait=0.5):
    t0 = time.time()
    out = orig_run_once(self, wait)
    if out:
        run_stats["n"] += 1
        run_stats["t"] += time.time() - t0
    return out


Scheduler.run_once = timed_run_once

start_inf = {"t": 0.0}
orig_start = SchedulerRunner.start


def timed_start(self, wait_sync=10.0, **kw):
    t0 = time.time()
    out = orig_start(self, wait_sync, **kw)
    start_inf["t"] = time.time() - t0
    return out


SchedulerRunner.start = timed_start

from benchmarks.connected import run_connected
res = run_connected(n_pods=int(os.environ.get("PODS", "2000")),
                    n_nodes=int(os.environ.get("NODES", "1000")),
                    log=lambda *a: print(*a, file=sys.stderr))
print(res)
print(f"runner.start (informer sync): {start_inf['t']:.2f}s")
print(f"run_once: n={run_stats['n']} total={run_stats['t']:.2f}s")
print(f"bind_one: n={bind_stats['n']} total={bind_stats['t']:.2f}s "
      f"avg={1000*bind_stats['t']/max(bind_stats['n'],1):.1f}ms")
agg = collections.defaultdict(lambda: [0, 0.0])
for s in TRACER.spans():
    agg[s.name][0] += 1
    agg[s.name][1] += s.duration_ms
for name, (n, ms) in sorted(agg.items(), key=lambda kv: -kv[1][1]):
    print(f"  span {name}: n={n} total={ms/1000:.2f}s")
