"""scheduler_perf — YAML-driven scheduling benchmark harness.

Reference: ``test/integration/scheduler_perf/scheduler_perf.go``
(``BenchmarkPerfScheduling``: each test case is an op list — createNodes,
createPods[, churn] — bound to named workloads via ``$param`` substitution;
the SchedulingThroughput collector measures pods/s over the
``collectMetrics: true`` pods; per-workload thresholds gate pass/fail;
``labels`` select subsets like the upstream ``performance``/``short`` tags).

The execution engine here is the TPU gang scheduler driven in-process (the
measured cycle is filter->score->select, exactly what the reference's
collector measures — binding is async in both).

Usage:
  python benchmarks/scheduler_perf.py [--labels short] [--case SchedulingBasic]
                                      [--scale 0.1] [--serial-oracle]
Emits one JSON line per workload:
  {"case": ..., "workload": ..., "SchedulingThroughput": ..., "passed": ...}
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

CONFIG_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "config")


def _sub(value, params):
    """$param substitution (scheduler_perf's countParam convention)."""
    if isinstance(value, str) and value.startswith("$"):
        return params[value[1:]]
    return value


def load_config(path=None):
    import yaml
    path = path or os.path.join(CONFIG_DIR, "performance-config.yaml")
    with open(path) as f:
        return yaml.safe_load(f)


def _load_template(rel_path):
    import yaml
    with open(os.path.join(CONFIG_DIR, rel_path)) as f:
        return yaml.safe_load(f)


def materialize(case: dict, params: dict):
    """Run the op list host-side -> (nodes, measured_pods, warm_pods)."""
    from kubernetes_tpu.api.types import Node, Pod

    nodes: list = []
    measured: list = []
    warm: list = []
    for op in case["workloadTemplate"]:
        code = op["opcode"]
        if code == "createNodes":
            count = int(_sub(op.get("countParam", op.get("count", 0)), params))
            tpl = _load_template(op["nodeTemplatePath"])
            strat = op.get("labelStrategy")
            for i in range(count):
                d = json.loads(json.dumps(tpl))
                md = d.setdefault("metadata", {})
                md["name"] = f"{md.pop('generateName', 'node-')}{i}"
                if strat:
                    md.setdefault("labels", {})[strat["key"]] = \
                        strat["values"][i % len(strat["values"])]
                md.setdefault("labels", {})["kubernetes.io/hostname"] = md["name"]
                nodes.append(Node.from_dict(d))
        elif code == "createPods":
            count = int(_sub(op.get("countParam", op.get("count", 0)), params))
            tpl = _load_template(op["podTemplatePath"])
            out = measured if op.get("collectMetrics") else warm
            for i in range(count):
                d = json.loads(json.dumps(tpl))
                md = d.setdefault("metadata", {})
                md["name"] = f"{md.pop('generateName', 'pod-')}{len(out)}-{i}"
                out.append(Pod.from_dict(d))
        elif code in ("simulateAutoscale", "simulateDefrag"):
            pass  # handled by the dedicated workload runner after materialize
        elif code == "generateWorkload":
            from benchmarks.workloads import WORKLOADS
            gen = WORKLOADS[op["generator"]]
            n_nodes = int(_sub(op["nodesParam"], params))
            n_pods = int(_sub(op["podsParam"], params))
            g_nodes, g_pods = gen(pods=n_pods, nodes=n_nodes)
            nodes.extend(g_nodes)
            (measured if op.get("collectMetrics") else warm).extend(g_pods)
        else:
            raise ValueError(f"unknown opcode {code!r}")
    return nodes, measured, warm


def run_workload(case: dict, workload: dict, scale: float = 1.0,
                 batch: int = 1024, log=lambda *a: None):
    """-> result dict with SchedulingThroughput + threshold verdicts."""
    from kubernetes_tpu.encode.snapshot import SnapshotEncoder
    from kubernetes_tpu.models.gang import gang_drain, prepare_drain

    params = {k: max(1, int(v * scale)) for k, v in workload["params"].items()}
    churn_op = next((op for op in case["workloadTemplate"]
                     if op["opcode"] == "churn"), None)
    if churn_op is not None:
        return _run_churn_workload(case, workload, params, churn_op, log,
                                   scale=scale, batch=batch)
    autoscale_op = next((op for op in case["workloadTemplate"]
                         if op["opcode"] == "simulateAutoscale"), None)
    if autoscale_op is not None:
        return _run_autoscaler_workload(case, workload, params,
                                        autoscale_op, log, scale=scale)
    defrag_op = next((op for op in case["workloadTemplate"]
                      if op["opcode"] == "simulateDefrag"), None)
    if defrag_op is not None:
        return _run_descheduler_workload(case, workload, params,
                                         defrag_op, log, scale=scale)
    nodes, measured, warm = materialize(case, params)
    log(f"  materialized {len(nodes)} nodes, {len(measured)} measured pods")

    enc = SnapshotEncoder()
    t0 = time.time()
    ct, meta = enc.encode_cluster(nodes, warm, pending_pods=measured,
                                  pending_slots=False)
    batches = [measured[i:i + batch] for i in range(0, len(measured), batch)]
    pbs = [enc.encode_pods(b, meta) for b in batches]
    topo_keys = meta.topo_keys
    # prepare_drain stages the cluster + queue tensors into HBM (a live
    # scheduler keeps them resident and patches deltas — sched/cache.py);
    # staging counts as encode time, not scheduling time.
    plan = prepare_drain(ct, pbs)
    encode_s = time.time() - t0

    # warmup compile (excluded, as upstream excludes informer warmup):
    # the drain is one program, so warmup = one full run on the same shapes
    t0 = time.time()
    gang_drain(topo_keys=topo_keys, prepared=plan)
    compile_s = time.time() - t0

    # The measured run drains the WHOLE queue as one device program
    # (lax.scan over batches — see models/gang.py gang_drain): one dispatch,
    # one readback; capacity and relational state carry batch to batch
    # exactly like the reference's sequential loop.
    t0 = time.time()
    assignments, rounds, _ = gang_drain(topo_keys=topo_keys, prepared=plan)
    dt = time.time() - t0
    scheduled = 0
    for b, chunk in enumerate(batches):
        scheduled += int((assignments[b][:len(chunk)] >= 0).sum())
    throughput = scheduled / dt if dt > 0 else 0.0
    # p99 per-pod schedule latency: every pod in a batch experiences its
    # batch's filter->score->select window (the decision is batch-atomic,
    # matching what scheduler_perf's attempt-duration metric measures). The
    # drain is one fused program, so batch windows are attributed from the
    # per-batch convergence round counts the device reports.
    total_rounds = max(int(rounds.sum()), 1)
    batch_s = [dt * int(r) / total_rounds for r in rounds]
    per_pod = np.repeat(batch_s[:len(batches)],
                        [len(c) for c in batches])
    p99 = float(np.percentile(per_pod, 99)) if per_pod.size else 0.0

    thresholds = workload.get("thresholds") or {}
    passed = all(throughput >= t * scale if k == "SchedulingThroughput" else True
                 for k, t in thresholds.items())
    if "p99ScheduleLatencySeconds" in thresholds:
        passed = passed and p99 <= thresholds["p99ScheduleLatencySeconds"]
    return {
        "case": case["name"], "workload": workload["name"],
        "SchedulingThroughput": round(throughput, 1),
        "p99_schedule_latency_s": round(p99, 4),
        "scheduled": scheduled, "pods": len(measured), "nodes": len(nodes),
        "encode_s": round(encode_s, 2), "compile_s": round(compile_s, 2),
        "measure_s": round(dt, 2),
        "thresholds": thresholds, "passed": passed,
    }


def _run_autoscaler_workload(case: dict, workload: dict, params: dict,
                             op: dict, log, scale: float = 1.0) -> dict:
    """The ``simulateAutoscale`` opcode: a full cluster (warm pods bound
    round-robin), the measured pods pending, and K candidate node groups
    evaluated by the batched tensor scale-up simulation — the measured
    quantity is the autoscaler DECISION latency (one ``run_filters`` over
    all K expansion hypotheses + the per-group binpack + the expander).
    Reference workload shape: the reference autoscaler's scalability tests
    measure the same RunOnce simulate phase."""
    from kubernetes_tpu.autoscaler.expander import EXPANDERS
    from kubernetes_tpu.autoscaler.nodegroup import load_node_group
    from kubernetes_tpu.autoscaler.simulator import simulate_scale_up

    nodes, measured, warm = materialize(case, params)
    # warm pods model the existing load: bind them round-robin so the
    # initial cluster is genuinely full for the pending set
    for i, p in enumerate(warm):
        p.spec.node_name = nodes[i % len(nodes)].metadata.name
    groups = [load_node_group(_load_template(path))
              for path in op["nodeGroupTemplatePaths"]]
    expander = EXPANDERS[op.get("expander", "least-waste")]
    log(f"  {len(nodes)} full nodes, {len(measured)} pending pods, "
        f"{len(groups)} candidate groups")

    # warmup excluded (JIT compile of the filter program), as everywhere
    t0 = time.time()
    simulate_scale_up(nodes, warm, measured, groups)
    compile_s = time.time() - t0
    t0 = time.time()
    options = simulate_scale_up(nodes, warm, measured, groups)
    decision_s = time.time() - t0
    choice = expander(options, seed=0)

    placed = choice.pods_placed if choice else 0
    thresholds = workload.get("thresholds") or {}
    passed = placed >= len(measured)
    if "ScaleUpDecisionSeconds" in thresholds:
        passed = passed and decision_s <= thresholds["ScaleUpDecisionSeconds"]
    return {
        "case": case["name"], "workload": workload["name"],
        "ScaleUpDecisionSeconds": round(decision_s, 4),
        "compile_s": round(compile_s, 2),
        "candidate_groups": len(groups),
        "pods_placed": placed, "pods": len(measured), "nodes": len(nodes),
        "chosen_group": choice.group.name if choice else None,
        "nodes_needed": choice.nodes_needed if choice else 0,
        "thresholds": thresholds, "passed": passed,
    }


def _run_descheduler_workload(case: dict, workload: dict, params: dict,
                              op: dict, log, scale: float = 1.0) -> dict:
    """The ``simulateDefrag`` opcode: a deliberately fragmented cluster
    (warm pods scattered one per node so no node can host a gang member)
    plus a pending gang — the measured quantity is the gang-defrag PLAN
    latency: one batched ``run_filters`` over every candidate drain prefix
    AND the gang, then the host-side fewest-evictions ledger scan
    (kubernetes_tpu/descheduler/planner.py plan_gang_defrag)."""
    from kubernetes_tpu.descheduler import (
        gang_consolidation_candidates,
        plan_gang_defrag,
    )

    nodes, measured, warm = materialize(case, params)
    for i, p in enumerate(warm):
        p.spec.node_name = nodes[i % len(nodes)].metadata.name
    max_nodes = int(_sub(op.get("maxDrainNodesParam",
                                op.get("maxDrainNodes", len(nodes))),
                         params))
    log(f"  {len(nodes)} fragmented nodes, {len(measured)} gang pods, "
        f"drain prefixes capped at {max_nodes}")

    def _plan():
        cands = gang_consolidation_candidates(nodes, warm,
                                              max_nodes=max_nodes)
        return plan_gang_defrag(nodes, warm, measured, "bench", cands)

    # warmup excluded (JIT compile of the filter program), as everywhere
    t0 = time.time()
    _plan()
    compile_s = time.time() - t0
    t0 = time.time()
    plan = _plan()
    plan_s = time.time() - t0

    seated = len(plan.gang_moves)
    thresholds = workload.get("thresholds") or {}
    passed = seated >= len(measured)
    if "DefragPlanSeconds" in thresholds:
        passed = passed and plan_s <= thresholds["DefragPlanSeconds"]
    return {
        "case": case["name"], "workload": workload["name"],
        "DefragPlanSeconds": round(plan_s, 4),
        "compile_s": round(compile_s, 2),
        "batch_victims": plan.batch_victims,
        "candidate_sets": plan.batch_sets,
        "evictions": plan.evictions,
        "gang_seated": seated, "pods": len(measured), "nodes": len(nodes),
        "thresholds": thresholds, "passed": passed,
    }


def _run_churn_workload(case: dict, workload: dict, params: dict,
                        churn_op: dict, log, scale: float = 1.0,
                        batch: int = 512) -> dict:
    """The ``churn`` opcode (upstream scheduler_perf's API-churn op): churn
    is an INTEGRATION-level behavior — nodes and unrelated pods recycling
    through the API while the measured pods schedule — so it runs through
    the CONNECTED harness (live apiserver + informers + the resident drain
    context's invalidate-and-rebuild path), not the raw device drain.
    Reference: test/integration/scheduler_perf/scheduler_perf.go
    (churnOp, Recreate mode)."""
    from benchmarks.connected import run_connected
    mode = churn_op.get("mode", "recreate")
    if mode != "recreate":
        raise ValueError(f"churn mode {mode!r} not implemented "
                         "(only 'recreate')")
    res = run_connected(
        n_pods=int(params["measurePods"]), n_nodes=int(params["initNodes"]),
        batch_size=min(batch, 512), churn=True,
        churn_period_s=float(churn_op.get("intervalMilliseconds", 100))
        / 1000.0,
        log=log)
    thresholds = workload.get("thresholds") or {}
    throughput = res["SchedulingThroughput"]
    passed = (res["bound"] >= res["pods"]
              and all(throughput >= t * scale
                      for k, t in thresholds.items()
                      if k == "SchedulingThroughput"))
    # HARD SLO gates (distinct from the advisory thresholds above): a
    # missing or regressed p99/throughput figure must fail the bench run,
    # not read as fine — bench.py exits non-zero on slo_failures.
    # Throughput floors scale with the workload like the advisory
    # thresholds do; latency ceilings stay absolute (a scaled-down run is
    # only ever faster).
    from benchmarks.connected import check_slo_gates
    slo = {k: (v * scale if k == "SchedulingThroughput" else v)
           for k, v in (workload.get("sloGates") or {}).items()}
    slo_failures = check_slo_gates(res, slo)
    return {
        "case": case["name"], "workload": workload["name"],
        "SchedulingThroughput": throughput,
        "p99_attempt_latency_s": res.get("p99_attempt_latency_s"),
        "p99_schedule_latency_s": res.get("p99_attempt_latency_s"),
        "scheduled": res["bound"], "pods": res["pods"],
        "nodes": res["nodes"], "measure_s": res["measure_s"],
        "churn_api_ops": res.get("churn_api_ops", 0),
        "ctx_stats": res.get("ctx_stats"),
        "connected": True,
        "thresholds": thresholds, "passed": passed and not slo_failures,
        "slo_gates": slo, "slo_failures": slo_failures,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--labels", default=None,
                    help="only workloads carrying this label (e.g. short)")
    ap.add_argument("--case", default=None, help="only this test case")
    ap.add_argument("--scale", type=float, default=1.0,
                    help="scale all counts (0.1 = 10%% size)")
    ap.add_argument("--batch", type=int, default=1024)
    ap.add_argument("--config", default=None)
    args = ap.parse_args(argv)

    cases = load_config(args.config)
    failed = 0
    for case in cases:
        if args.case and case["name"] != args.case:
            continue
        for workload in case["workloads"]:
            if args.labels and args.labels not in (workload.get("labels") or []):
                continue
            res = run_workload(case, workload, scale=args.scale,
                               batch=args.batch,
                               log=lambda *a: print(*a, file=sys.stderr))
            print(json.dumps(res))
            if not res["passed"]:
                failed += 1
    return 1 if failed else 0


if __name__ == "__main__":
    raise SystemExit(main())
