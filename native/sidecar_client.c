/* Native (C) client for the ktpu scheduling sidecar.
 *
 * Proves the process boundary SURVEY §7 phase 7 requires: a NON-Python
 * consumer speaking the sidecar's wire protocol — gRPC (HTTP/2, 5-byte
 * length-prefixed frames) carrying msgpack maps — the shape of the Go
 * scheduler shim that replaces pkg/scheduler/extender.go's HTTPExtender.
 *
 * No generated code and no grpc library: a ~100-line msgpack codec plus
 * libcurl's HTTP/2 support (dlopen'd — the image ships the shared object
 * without dev headers) is the whole client, exactly the "three-line codec"
 * promise the protocol makes (sidecar/proto.py).
 *
 * Exercises, against a live sidecar/server.py:
 *   1. PushSnapshot   N nodes, generation 1
 *   2. Schedule       P pods -> every pod placed on a real node
 *   3. PushDelta      bind the placements (ordered upsert ops) -> gen 2
 *   4. Schedule       STALE generation -> {stale: true, server_generation}
 *   5. Schedule       wave 2 at gen 2 -> placements reflect wave 1's usage
 *   6. PushDelta      node_delete + delete ops replay in ORDER -> gen 3
 *
 * Usage: sidecar_client <host:port> [nodes] [pods]
 * Exit 0 = every check passed.
 */

#include <dlfcn.h>
#include <stdarg.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

/* ------------------------------------------------------------ msgpack out */

typedef struct {
    uint8_t *buf;
    size_t len, cap;
} mp_out;

static void mp_reserve(mp_out *o, size_t extra) {
    if (o->len + extra <= o->cap) return;
    o->cap = (o->cap ? o->cap * 2 : 4096);
    while (o->cap < o->len + extra) o->cap *= 2;
    o->buf = realloc(o->buf, o->cap);
}

static void mp_byte(mp_out *o, uint8_t b) { mp_reserve(o, 1); o->buf[o->len++] = b; }
static void mp_raw(mp_out *o, const void *p, size_t n) {
    mp_reserve(o, n); memcpy(o->buf + o->len, p, n); o->len += n;
}

static void mp_uint(mp_out *o, uint64_t v) {
    if (v < 0x80) { mp_byte(o, (uint8_t)v); }
    else if (v <= 0xff) { mp_byte(o, 0xcc); mp_byte(o, (uint8_t)v); }
    else if (v <= 0xffff) { mp_byte(o, 0xcd); mp_byte(o, v >> 8); mp_byte(o, v); }
    else if (v <= 0xffffffffu) {
        mp_byte(o, 0xce);
        for (int i = 3; i >= 0; i--) mp_byte(o, (uint8_t)(v >> (8 * i)));
    } else {
        mp_byte(o, 0xcf);
        for (int i = 7; i >= 0; i--) mp_byte(o, (uint8_t)(v >> (8 * i)));
    }
}

static void mp_str(mp_out *o, const char *s) {
    size_t n = strlen(s);
    if (n < 32) mp_byte(o, 0xa0 | (uint8_t)n);
    else if (n <= 0xff) { mp_byte(o, 0xd9); mp_byte(o, (uint8_t)n); }
    else { mp_byte(o, 0xda); mp_byte(o, n >> 8); mp_byte(o, n); }
    mp_raw(o, s, n);
}

static void mp_map(mp_out *o, uint32_t n) {
    if (n < 16) mp_byte(o, 0x80 | (uint8_t)n);
    else { mp_byte(o, 0xde); mp_byte(o, n >> 8); mp_byte(o, n); }
}

static void mp_arr(mp_out *o, uint32_t n) {
    if (n < 16) mp_byte(o, 0x90 | (uint8_t)n);
    else { mp_byte(o, 0xdc); mp_byte(o, n >> 8); mp_byte(o, n); }
}

/* ------------------------------------------------------------- msgpack in */

typedef struct {
    const uint8_t *p, *end;
    int err;
} mp_in;

static uint64_t mp_be(mp_in *in, int n) {
    uint64_t v = 0;
    if (in->end - in->p < n) { in->err = 1; return 0; }
    for (int i = 0; i < n; i++) v = (v << 8) | *in->p++;
    return v;
}

/* skip one value of any type */
static void mp_skip(mp_in *in);

/* returns type tag class: 'i' int, 's' str (fills sp/sn), 'a' array (*n),
 * 'm' map (*n), 'b' bool (*n = 0/1), 'n' nil, '?' other (skipped) */
static char mp_next(mp_in *in, const char **sp, uint32_t *n) {
    if (in->p >= in->end) { in->err = 1; return '?'; }
    uint8_t t = *in->p++;
    if (t < 0x80 || t >= 0xe0) { if (n) *n = (uint32_t)(int8_t)t; return 'i'; }
    if ((t & 0xf0) == 0x80) { if (n) *n = t & 0x0f; return 'm'; }
    if ((t & 0xf0) == 0x90) { if (n) *n = t & 0x0f; return 'a'; }
    if ((t & 0xe0) == 0xa0) {
        uint32_t ln = t & 0x1f;
        if (in->end - in->p < ln) { in->err = 1; return '?'; }
        if (sp) *sp = (const char *)in->p;
        if (n) *n = ln;
        in->p += ln;
        return 's';
    }
    switch (t) {
    case 0xc0: return 'n';
    case 0xc2: if (n) *n = 0; return 'b';
    case 0xc3: if (n) *n = 1; return 'b';
    case 0xcc: if (n) *n = (uint32_t)mp_be(in, 1); return 'i';
    case 0xcd: if (n) *n = (uint32_t)mp_be(in, 2); return 'i';
    case 0xce: if (n) *n = (uint32_t)mp_be(in, 4); return 'i';
    case 0xcf: if (n) *n = (uint32_t)mp_be(in, 8); return 'i';
    case 0xd0: if (n) *n = (uint32_t)(int8_t)mp_be(in, 1); return 'i';
    case 0xd1: if (n) *n = (uint32_t)(int16_t)mp_be(in, 2); return 'i';
    case 0xd2: if (n) *n = (uint32_t)(int32_t)mp_be(in, 4); return 'i';
    case 0xd3: if (n) *n = (uint32_t)mp_be(in, 8); return 'i';
    case 0xd9: case 0xda: case 0xdb: {
        uint32_t ln = (uint32_t)mp_be(in, t == 0xd9 ? 1 : t == 0xda ? 2 : 4);
        if (in->end - in->p < ln) { in->err = 1; return '?'; }
        if (sp) *sp = (const char *)in->p;
        if (n) *n = ln;
        in->p += ln;
        return 's';
    }
    case 0xc4: case 0xc5: case 0xc6: {  /* bin: treat as str */
        uint32_t ln = (uint32_t)mp_be(in, t == 0xc4 ? 1 : t == 0xc5 ? 2 : 4);
        if (in->end - in->p < ln) { in->err = 1; return '?'; }
        if (sp) *sp = (const char *)in->p;
        if (n) *n = ln;
        in->p += ln;
        return 's';
    }
    case 0xca: mp_be(in, 4); if (n) *n = 0; return 'i';  /* f32: not needed */
    case 0xcb: mp_be(in, 8); if (n) *n = 0; return 'i';  /* f64 */
    case 0xdc: if (n) *n = (uint32_t)mp_be(in, 2); return 'a';
    case 0xdd: if (n) *n = (uint32_t)mp_be(in, 4); return 'a';
    case 0xde: if (n) *n = (uint32_t)mp_be(in, 2); return 'm';
    case 0xdf: if (n) *n = (uint32_t)mp_be(in, 4); return 'm';
    default: in->err = 1; return '?';
    }
}

static void mp_skip(mp_in *in) {
    uint32_t n = 0;
    switch (mp_next(in, NULL, &n)) {
    case 'm': for (uint32_t i = 0; i < 2 * n && !in->err; i++) mp_skip(in); break;
    case 'a': for (uint32_t i = 0; i < n && !in->err; i++) mp_skip(in); break;
    default: break;
    }
}

/* --------------------------------------------------------- libcurl dlopen */

typedef void CURL;
struct curl_slist;

static struct {
    CURL *(*easy_init)(void);
    int (*easy_setopt)(CURL *, int, ...);
    int (*easy_perform)(CURL *);
    void (*easy_cleanup)(CURL *);
    long (*easy_getinfo)(CURL *, int, ...);
    struct curl_slist *(*slist_append)(struct curl_slist *, const char *);
    void (*slist_free_all)(struct curl_slist *);
} cu;

/* option codes from curl.h (stable ABI) */
#define CURLOPT_URL 10002
#define CURLOPT_POSTFIELDS 10015
#define CURLOPT_POSTFIELDSIZE 60
#define CURLOPT_HTTPHEADER 10023
#define CURLOPT_WRITEFUNCTION 20011
#define CURLOPT_WRITEDATA 10001
#define CURLOPT_POST 47
#define CURLOPT_HTTP_VERSION 84
#define CURL_HTTP_VERSION_2_PRIOR_KNOWLEDGE 5
#define CURLINFO_RESPONSE_CODE 0x200002

static int cu_load(void) {
    void *h = dlopen("libcurl.so.4", RTLD_NOW);
    if (!h) h = dlopen("libcurl-gnutls.so.4", RTLD_NOW);
    if (!h) { fprintf(stderr, "FAIL: no libcurl\n"); return -1; }
    cu.easy_init = dlsym(h, "curl_easy_init");
    cu.easy_setopt = dlsym(h, "curl_easy_setopt");
    cu.easy_perform = dlsym(h, "curl_easy_perform");
    cu.easy_cleanup = dlsym(h, "curl_easy_cleanup");
    cu.easy_getinfo = dlsym(h, "curl_easy_getinfo");
    cu.slist_append = dlsym(h, "curl_slist_append");
    cu.slist_free_all = dlsym(h, "curl_slist_free_all");
    return (cu.easy_init && cu.easy_setopt && cu.easy_perform &&
            cu.slist_append) ? 0 : -1;
}

typedef struct { uint8_t *buf; size_t len, cap; } blob;

static size_t on_body(char *ptr, size_t sz, size_t nm, void *ud) {
    blob *b = ud;
    size_t n = sz * nm;
    if (b->len + n > b->cap) {
        b->cap = (b->cap ? b->cap * 2 : 8192);
        while (b->cap < b->len + n) b->cap *= 2;
        b->buf = realloc(b->buf, b->cap);
    }
    memcpy(b->buf + b->len, ptr, n);
    b->len += n;
    return n;
}

/* one gRPC unary call: msgpack payload in, msgpack payload out */
static int grpc_call(const char *base, const char *method,
                     const mp_out *req, blob *resp) {
    char url[512];
    snprintf(url, sizeof url, "http://%s/ktpu.SchedSidecar/%s", base, method);
    /* 5-byte gRPC frame: flags=0 + big-endian length */
    size_t flen = 5 + req->len;
    uint8_t *frame = malloc(flen);
    frame[0] = 0;
    for (int i = 0; i < 4; i++)
        frame[1 + i] = (uint8_t)(req->len >> (8 * (3 - i)));
    memcpy(frame + 5, req->buf, req->len);

    CURL *h = cu.easy_init();
    struct curl_slist *hdr = NULL;
    hdr = cu.slist_append(hdr, "Content-Type: application/grpc");
    hdr = cu.slist_append(hdr, "TE: trailers");
    hdr = cu.slist_append(hdr, "Expect:");
    cu.easy_setopt(h, CURLOPT_URL, url);
    cu.easy_setopt(h, CURLOPT_HTTP_VERSION,
                   (long)CURL_HTTP_VERSION_2_PRIOR_KNOWLEDGE);
    cu.easy_setopt(h, CURLOPT_POST, 1L);
    cu.easy_setopt(h, CURLOPT_POSTFIELDS, frame);
    cu.easy_setopt(h, CURLOPT_POSTFIELDSIZE, (long)flen);
    cu.easy_setopt(h, CURLOPT_HTTPHEADER, hdr);
    cu.easy_setopt(h, CURLOPT_WRITEFUNCTION, on_body);
    cu.easy_setopt(h, CURLOPT_WRITEDATA, resp);
    int rc = cu.easy_perform(h);
    long code = 0;
    if (cu.easy_getinfo) cu.easy_getinfo(h, CURLINFO_RESPONSE_CODE, &code);
    cu.slist_free_all(hdr);
    cu.easy_cleanup(h);
    free(frame);
    if (rc != 0 || code != 200) {
        fprintf(stderr, "FAIL: %s transport rc=%d http=%ld\n", method, rc, code);
        return -1;
    }
    if (resp->len < 5) {
        fprintf(stderr, "FAIL: %s short gRPC frame (%zu)\n", method, resp->len);
        return -1;
    }
    /* strip the response's 5-byte frame header in place */
    memmove(resp->buf, resp->buf + 5, resp->len - 5);
    resp->len -= 5;
    return 0;
}

/* ----------------------------------------------------------- domain logic */

static void enc_node(mp_out *o, int i) {
    char name[32], cpu[16];
    snprintf(name, sizeof name, "cn-%d", i);
    snprintf(cpu, sizeof cpu, "%d", 4);
    mp_map(o, 3);
    mp_str(o, "kind"); mp_str(o, "Node");
    mp_str(o, "metadata"); mp_map(o, 1); mp_str(o, "name"); mp_str(o, name);
    mp_str(o, "status"); mp_map(o, 1);
    mp_str(o, "allocatable"); mp_map(o, 3);
    mp_str(o, "cpu"); mp_str(o, cpu);
    mp_str(o, "memory"); mp_str(o, "8Gi");
    mp_str(o, "pods"); mp_str(o, "16");
}

static void enc_pod(mp_out *o, const char *name, const char *node) {
    mp_map(o, 3);
    mp_str(o, "kind"); mp_str(o, "Pod");
    mp_str(o, "metadata"); mp_map(o, 2);
    mp_str(o, "name"); mp_str(o, name);
    mp_str(o, "namespace"); mp_str(o, "default");
    mp_str(o, "spec");
    mp_map(o, node ? 2 : 1);
    mp_str(o, "containers"); mp_arr(o, 1);
    mp_map(o, 2);
    mp_str(o, "name"); mp_str(o, "c");
    mp_str(o, "resources"); mp_map(o, 1);
    mp_str(o, "requests"); mp_map(o, 2);
    mp_str(o, "cpu"); mp_str(o, "500m");
    mp_str(o, "memory"); mp_str(o, "256Mi");
    if (node) { mp_str(o, "nodeName"); mp_str(o, node); }
}

/* find a top-level key in a response map; returns type via mp_next contract */
static char find_key(blob *resp, const char *key, const char **sp,
                     uint32_t *n, mp_in *save) {
    mp_in in = { resp->buf, resp->buf + resp->len, 0 };
    uint32_t pairs = 0;
    if (mp_next(&in, NULL, &pairs) != 'm') return '?';
    for (uint32_t i = 0; i < pairs && !in.err; i++) {
        const char *kp; uint32_t kn = 0;
        if (mp_next(&in, &kp, &kn) != 's') return '?';
        if (kn == strlen(key) && !memcmp(kp, key, kn)) {
            char t = mp_next(&in, sp, n);
            if (save) *save = in;
            return t;
        }
        mp_skip(&in);
    }
    return 0;
}

static int expect_gen(blob *resp, const char *what, long want) {
    uint32_t v = 0;
    if (find_key(resp, "generation", NULL, &v, NULL) != 'i' ||
        (long)v != want) {
        fprintf(stderr, "FAIL: %s generation != %ld\n", what, want);
        return -1;
    }
    printf("OK %s -> generation %ld\n", what, want);
    return 0;
}

int main(int argc, char **argv) {
    if (argc < 2) { fprintf(stderr, "usage: %s host:port [N] [P]\n", argv[0]); return 2; }
    const char *base = argv[1];
    int N = argc > 2 ? atoi(argv[2]) : 100;
    int P = argc > 3 ? atoi(argv[3]) : 100;
    if (cu_load()) return 1;

    /* 1. PushSnapshot: N nodes, generation 1 */
    mp_out req = {0};
    mp_map(&req, 4);
    mp_str(&req, "nodes"); mp_arr(&req, (uint32_t)N);
    for (int i = 0; i < N; i++) enc_node(&req, i);
    mp_str(&req, "pods"); mp_arr(&req, 0);
    mp_str(&req, "generation"); mp_uint(&req, 1);
    mp_str(&req, "profile"); mp_map(&req, 1);
    mp_str(&req, "fit_strategy"); mp_str(&req, "LeastAllocated");
    blob resp = {0};
    if (grpc_call(base, "PushSnapshot", &req, &resp)) return 1;
    if (expect_gen(&resp, "PushSnapshot", 1)) return 1;

    /* 2. Schedule wave 1 */
    char (*placed)[64] = calloc((size_t)P, 64);
    req.len = 0; resp.len = 0;
    mp_map(&req, 2);
    mp_str(&req, "pods"); mp_arr(&req, (uint32_t)P);
    for (int i = 0; i < P; i++) {
        char name[32]; snprintf(name, sizeof name, "w1-%d", i);
        enc_pod(&req, name, NULL);
    }
    mp_str(&req, "generation"); mp_uint(&req, 1);
    if (grpc_call(base, "Schedule", &req, &resp)) return 1;
    {
        const char *sp; uint32_t n = 0; mp_in in;
        if (find_key(&resp, "assignments", &sp, &n, &in) != 'a' || n != (uint32_t)P) {
            fprintf(stderr, "FAIL: Schedule wave1 assignments\n"); return 1;
        }
        for (uint32_t i = 0; i < n; i++) {
            uint32_t sn = 0;
            if (mp_next(&in, &sp, &sn) != 's' || sn == 0 || sn >= 64) {
                fprintf(stderr, "FAIL: pod %u unplaced\n", i); return 1;
            }
            memcpy(placed[i], sp, sn);
        }
        printf("OK Schedule wave1 -> %d/%d pods placed\n", P, P);
    }

    /* 3. PushDelta: bind wave 1 (ordered upserts), generation 2 */
    req.len = 0; resp.len = 0;
    mp_map(&req, 3);
    mp_str(&req, "base_generation"); mp_uint(&req, 1);
    mp_str(&req, "generation"); mp_uint(&req, 2);
    mp_str(&req, "ops"); mp_arr(&req, (uint32_t)P);
    for (int i = 0; i < P; i++) {
        char name[32]; snprintf(name, sizeof name, "w1-%d", i);
        mp_map(&req, 2);
        mp_str(&req, "op"); mp_str(&req, "upsert");
        mp_str(&req, "pod"); enc_pod(&req, name, placed[i]);
    }
    if (grpc_call(base, "PushDelta", &req, &resp)) return 1;
    if (expect_gen(&resp, "PushDelta(bind wave1)", 2)) return 1;

    /* 4. STALE: schedule against the superseded generation */
    req.len = 0; resp.len = 0;
    mp_map(&req, 2);
    mp_str(&req, "pods"); mp_arr(&req, 1); enc_pod(&req, "stale-probe", NULL);
    mp_str(&req, "generation"); mp_uint(&req, 1);
    if (grpc_call(base, "Schedule", &req, &resp)) return 1;
    {
        uint32_t b = 0, sg = 0;
        if (find_key(&resp, "stale", NULL, &b, NULL) != 'b' || !b) {
            fprintf(stderr, "FAIL: stale generation not rejected\n"); return 1;
        }
        find_key(&resp, "server_generation", NULL, &sg, NULL);
        printf("OK Schedule(gen=1) -> STALE (server at %u)\n", sg);
    }

    /* 5. wave 2 at the current generation sees wave 1's usage */
    req.len = 0; resp.len = 0;
    mp_map(&req, 2);
    mp_str(&req, "pods"); mp_arr(&req, (uint32_t)P);
    for (int i = 0; i < P; i++) {
        char name[32]; snprintf(name, sizeof name, "w2-%d", i);
        enc_pod(&req, name, NULL);
    }
    mp_str(&req, "generation"); mp_uint(&req, 2);
    if (grpc_call(base, "Schedule", &req, &resp)) return 1;
    {
        const char *sp; uint32_t n = 0; mp_in in;
        if (find_key(&resp, "assignments", &sp, &n, &in) != 'a' || n != (uint32_t)P) {
            fprintf(stderr, "FAIL: Schedule wave2 shape\n"); return 1;
        }
        int placed2 = 0;
        for (uint32_t i = 0; i < n; i++) {
            uint32_t sn = 0;
            if (mp_next(&in, &sp, &sn) != 's') { fprintf(stderr, "FAIL w2\n"); return 1; }
            if (sn) placed2++;
        }
        printf("OK Schedule wave2 -> %d/%d placed at gen 2\n", placed2, P);
        if (placed2 == 0) { fprintf(stderr, "FAIL: wave2 empty\n"); return 1; }
    }

    /* 6. ordered ops: delete a node + delete a pod, generation 3 */
    req.len = 0; resp.len = 0;
    mp_map(&req, 3);
    mp_str(&req, "base_generation"); mp_uint(&req, 2);
    mp_str(&req, "generation"); mp_uint(&req, 3);
    mp_str(&req, "ops"); mp_arr(&req, 2);
    mp_map(&req, 2);
    mp_str(&req, "op"); mp_str(&req, "node_delete");
    mp_str(&req, "name"); mp_str(&req, "cn-0");
    mp_map(&req, 2);
    mp_str(&req, "op"); mp_str(&req, "delete");
    mp_str(&req, "key"); mp_str(&req, "default/w1-0");
    if (grpc_call(base, "PushDelta", &req, &resp)) return 1;
    if (expect_gen(&resp, "PushDelta(node_delete+delete)", 3)) return 1;

    printf("NATIVE SIDECAR CLIENT: ALL CHECKS PASSED\n");
    return 0;
}
